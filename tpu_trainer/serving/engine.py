"""Serving engine: jitted prefill/decode steps over the paged model path.

The engine owns a fixed slot batch (``max_batch`` rows). Every iteration
the scheduler picks ONE of:

- **prefill** — requests mid-prefill feed ``seq[cursor:cursor+chunk]``
  (width bucketed to a power of two so nearby shapes share a compile)
  at their global positional offset; with ``prefill_chunk_tokens`` set,
  a long prompt is split across iterations that alternate with decode
  steps, so no decode iteration waits more than one chunk. Chunks past
  offset 0 also attend the pooled history written by earlier chunks (or
  a shared prefix) through a static ``hist_blocks``-wide table gather.
  Feeding generated tokens too on re-admission is what makes
  recompute-preemption exact: a resumed request is indistinguishable
  from one that was never interrupted — same cache contents, same next
  sampling step.
- **decode** — every running request that finished prefill advances one
  token in a single ``[slots, 1]`` forward.

Both steps are one jitted dispatch including sampling (per-request
temperature / top-k / seed, ``serving/sampling.py``). The only
persistent device state is the KV block pools; block tables, lengths
and chunk offsets are re-broadcast from the scheduler's host mirrors
into the cache pytree *inside* the jit, so scheduling never syncs the
device. Idle and non-stepped rows have zeroed table rows and length 0:
their writes land in reserved block 0 and their sampled tokens are
ignored host-side (a mid-prefill chunk's sampled token is likewise
discarded — only the final chunk's draw, made at the same (seed, token
index) as an unchunked pass, is consumed), which keeps every step
unpredicated over the full slot batch.

``prefix_cache=True`` turns on copy-on-write prefix sharing in the
block pool (serving/paged_cache.py): after each chunk the engine
publishes newly completed full PROMPT blocks under their chained
content digest, and admission starts later identical prompts past the
shared blocks entirely.

``python -m tpu_trainer.serving.engine`` replays a seeded open-loop
Poisson arrival trace against a synthetic checkpoint and prints the
latency/throughput summary (see also benchmarks/serve_bench.py).
"""

from __future__ import annotations

import dataclasses
import functools
import time
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from tpu_trainer.models.config import GPTConfig
from tpu_trainer.models.gpt import GPT, init_paged_cache
from tpu_trainer.obs.metrics import NULL_REGISTRY
from tpu_trainer.serving.kv_store import KVBlockStore, MigrationPricer
from tpu_trainer.serving.paged_cache import PagedKVCache
from tpu_trainer.serving.sampling import sample_tokens
from tpu_trainer.serving.scheduler import Request, SamplingParams, Scheduler
from tpu_trainer.serving.spec import (
    DraftModelProposer,
    NGramProposer,
    SpecDecoder,
    _verify_step,
    draft_from_target,
)
from tpu_trainer.serving.tracing import ServingLedger, SpanTracer


def _bucket_pow2(n: int, lo: int = 8) -> int:
    w = lo
    while w < n:
        w *= 2
    return w


# Device-cache leaves that hold per-block K/V payload (int8 pools add the
# scale planes). Everything else in the cache pytree is scheduling state
# re-broadcast from host mirrors each step.
_POOL_LEAF_KEYS = ("pool_k", "pool_v", "scale_k", "scale_v")


class ServingEngine:
    """Continuous-batching engine over one model + parameter set."""

    def __init__(
        self,
        params,
        config: GPTConfig,
        *,
        max_batch: int = 8,
        block_size: int = 16,
        num_blocks: Optional[int] = None,
        max_blocks_per_request: Optional[int] = None,
        kv_int8: bool = False,
        attention: str = "auto",
        eos_id: Optional[int] = None,
        watermark_blocks: int = 0,
        prefill_chunk_tokens: Optional[int] = None,
        prefix_cache: bool = False,
        spec: str = "off",
        spec_k: int = 4,
        spec_adaptive: bool = True,
        spec_ngram_max: int = 3,
        draft_params=None,
        draft_config: Optional[GPTConfig] = None,
        spec_proposer=None,
        clock=time.perf_counter,
        trace: bool = True,
        ts_interval: int = 32,
        metric_logger=None,
        registry=None,
        mesh_tensor: Optional[int] = None,
        mesh_devices: Optional[Sequence[int]] = None,
        device_block_budget: Optional[int] = None,
        kv_store: Optional[KVBlockStore] = None,
        kv_store_bytes: Optional[int] = None,
        kv_store_dir: Optional[str] = None,
        kv_link_gbps: float = 16.0,
        role: Optional[str] = None,
    ):
        if spec not in ("off", "ngram", "draft"):
            raise ValueError(f"spec={spec!r} (off | ngram | draft)")
        if max_blocks_per_request is None:
            max_blocks_per_request = -(-config.max_seq_len // block_size)
        # Tensor parallel: one replica = one mesh (serving/sharding.py).
        # ``mesh_tensor`` is the mesh size; ``mesh_devices`` optionally
        # pins the exact device ids (a fleet of disjoint meshes on one
        # host); ``device_block_budget`` sizes the pool per DEVICE — with
        # kv-head-sharded pools each device holds 1/tp of every block, so
        # the replica affords budget * tp total blocks.
        tp = int(mesh_tensor) if mesh_tensor else 1
        if mesh_devices is not None:
            mesh_devices = tuple(int(d) for d in mesh_devices)
            if tp == 1 and len(mesh_devices) > 1:
                tp = len(mesh_devices)
        self.mesh_tensor = tp
        if device_block_budget is not None and num_blocks is None:
            from tpu_trainer.serving import sharding as tp_lib

            num_blocks = device_block_budget * tp_lib.shard_factor(
                config.kv_heads, tp)
        if num_blocks is None:
            # Enough for every slot to run at full context, + null block.
            num_blocks = max_batch * max_blocks_per_request + 1
        self.config = dataclasses.replace(
            config,
            dropout=0.0,
            attention_dropout=0.0,
            decode_paged=True,
            decode_ragged=False,
            paged_block_size=block_size,
            paged_num_blocks=num_blocks,
            paged_max_blocks=max_blocks_per_request,
            paged_kv_int8=kv_int8,
            paged_attention=attention,
            paged_tp=tp,
            paged_tp_devices=(mesh_devices if tp > 1 else None),
        )
        self.params = params
        self.max_batch = max_batch
        self.eos_id = eos_id
        self.clock = clock
        self.prefix_cache = prefix_cache
        # Fleet KV store (serving/kv_store.py): in-process replicas share
        # ONE object via ``kv_store``; cross-process workers each build a
        # local store from the scalar (wire-able) ``kv_store_bytes`` /
        # ``kv_store_dir`` kwargs and synchronize over the kv_* RPC verbs.
        self._owns_store = kv_store is None
        if kv_store is None and (kv_store_bytes or kv_store_dir):
            kv_store = KVBlockStore(
                host_bytes=int(kv_store_bytes) if kv_store_bytes
                else 64 << 20,
                disk_dir=kv_store_dir)
        self.kv_store = kv_store
        self._pool_leaf_idx: Optional[List[int]] = None
        self.cache_state = PagedKVCache(
            self.config, max_batch, prefix_cache=prefix_cache,
            kv_store=kv_store,
        )
        if kv_store is not None:
            self.cache_state.spill_fn = self._store_put_block
            self.cache_state.fill_fn = self._store_fill_block
            self.cache_state.raw_fill_fn = self.write_block
            self.cache_state.pricer = self._build_pricer(kv_link_gbps)
        # Speculative decoding: resolve the proposer before the
        # scheduler so admission can budget for the draft window.
        proposer = spec_proposer
        if proposer is None and spec == "ngram":
            proposer = NGramProposer(max_ngram=spec_ngram_max)
        elif proposer is None and spec == "draft":
            if draft_params is None or draft_config is None:
                raise ValueError(
                    "spec='draft' needs draft_params and draft_config "
                    "(see spec.draft_from_target)")
            if draft_config.vocab_size != config.vocab_size:
                raise ValueError("draft/target vocab mismatch")
            if draft_config.max_seq_len < config.max_seq_len:
                raise ValueError("draft max_seq_len < target max_seq_len")
            proposer = DraftModelProposer(
                draft_params, draft_config, slots=max_batch,
                block_size=block_size, attention=attention)
        self.spec_decoder = (
            SpecDecoder(proposer, k=spec_k, adaptive=spec_adaptive)
            if proposer is not None else None)
        self.scheduler = Scheduler(
            self.cache_state, watermark_blocks=watermark_blocks,
            prefill_chunk_tokens=prefill_chunk_tokens,
            spec_reserve_tokens=(
                spec_k + 1 if self.spec_decoder is not None else 0),
        )
        self.role: Optional[str] = None
        if role is not None:
            self.set_role(role)
        # Observability (serving/tracing.py): per-rid span timelines in
        # this engine's clock domain, and wall-clock attribution for the
        # run loop. Both host-side only — they can never perturb the
        # jitted path, so token streams are bit-identical trace on/off.
        self.tracer = SpanTracer(enabled=trace)
        self.scheduler.tracer = self.tracer
        self.scheduler.now_fn = self._now
        self.ledger = ServingLedger()
        self.ts_interval = int(ts_interval)
        self.metric_logger = metric_logger
        self.serve_ts: List[dict] = []
        self.device_cache = init_paged_cache(self.config, max_batch)
        if tp > 1:
            # Commit the replica's persistent device state to the mesh:
            # pools sharded on kv heads (when divisible), params sharded
            # on each leaf's largest tp-divisible axis (~P/tp resident
            # per device; the step gathers them back exactly — see
            # serving/sharding.py for why greedy streams stay
            # token-identical).
            from tpu_trainer.serving import sharding as tp_lib

            mesh = tp_lib.tp_mesh(tp, self.config.paged_tp_devices)
            self.params = tp_lib.shard_params(self.params, mesh)
            self.device_cache = tp_lib.shard_cache(
                self.device_cache, mesh, self.config.kv_heads)
        self._model = GPT(self.config)
        self._step_jit = _jitted_engine_step(self.config)
        self._verify_jit = _jitted_verify_step(self.config)
        self._k_cap = 1
        self._iters = 0
        self._t0 = None
        # Per deadline-carrying terminal request: terminal_time - deadline
        # (positive = the deadline was missed by that much). Feeds the
        # deadline_miss_* summary fields.
        self._deadline_margins: List[float] = []
        self.stats: Dict[str, float] = {
            "prefill_iters": 0, "decode_iters": 0, "idle_iters": 0,
            "prefill_tokens": 0, "prefill_chunks": 0,
            "generated_tokens": 0,
            "occupancy_sum": 0.0, "occupancy_samples": 0,
            "occupancy_max": 0.0,
            "spec_steps": 0, "spec_drafted": 0, "spec_accepted": 0,
            # Per-terminal-state request counts ("failed" has no current
            # producer — see scheduler.TERMINAL_STATES).
            "finished": 0, "cancelled": 0, "deadline_exceeded": 0,
            "failed": 0,
        }
        # Live metrics plane (obs/): counters and gauges mirror the
        # cumulative stats above via set_function — read at scrape time,
        # zero hot-path cost, and exact agreement with summary() by
        # construction. Only the latency histograms observe inline, and
        # those sites are no-op method calls on the null registry.
        self.registry = registry if registry is not None else NULL_REGISTRY
        self._metrics_on = registry is not None
        self._install_metrics()

    def _install_metrics(self) -> None:
        reg = self.registry
        self._m_step_seconds = reg.histogram(
            "serve_step_seconds", "Engine step wall-clock latency")
        self._m_ttft = reg.histogram(
            "serve_ttft_seconds", "Time to first token (engine clock)")
        self._m_tpot = reg.histogram(
            "serve_tpot_seconds", "Inter-token gap (engine clock)")
        req_total = reg.counter(
            "serve_requests_total", "Terminal requests by state",
            labelnames=("state",))
        for state in self.scheduler.terminal_counts:
            req_total.labels(state=state).set_function(
                lambda s=state: self.scheduler.terminal_counts[s])
        reg.counter("serve_admissions_total", "Admission events "
                    "(re-admission after preemption/failover counts)"
                    ).set_function(lambda: self.scheduler.n_admissions)
        reg.counter("serve_preemptions_total", "Recompute preemptions"
                    ).set_function(lambda: self.scheduler.n_preemptions)
        reg.counter("serve_generated_tokens_total", "Tokens emitted"
                    ).set_function(lambda: self.stats["generated_tokens"])
        reg.counter("serve_prefill_tokens_total", "Prompt tokens prefilled"
                    ).set_function(lambda: self.stats["prefill_tokens"])
        reg.counter("serve_prompt_tokens_total", "Prompt tokens admitted"
                    ).set_function(lambda: self.scheduler.prompt_tokens)
        reg.counter("serve_prefix_hit_tokens_total",
                    "Prompt tokens served from the prefix index"
                    ).set_function(lambda: self.scheduler.prefix_hit_tokens)
        reg.counter("serve_prefix_evictions_total", "Prefix-index evictions"
                    ).set_function(
                        lambda: self.cache_state.n_prefix_evictions)
        pool = reg.gauge("serve_pool_blocks",
                         "Paged-pool fragmentation split",
                         labelnames=("kind",))
        pool.labels(kind="free").set_function(
            lambda: self.cache_state.pool.free_blocks)
        pool.labels(kind="evictable").set_function(
            lambda: self.cache_state.evictable_blocks)
        pool.labels(kind="referenced").set_function(
            lambda: self.cache_state.referenced_blocks)
        reg.gauge("serve_pool_occupancy", "Paged-pool occupancy fraction"
                  ).set_function(lambda: self.cache_state.pool.occupancy)
        reg.gauge("serve_prefix_index_entries", "Prefix-index size"
                  ).set_function(
                      lambda: self.cache_state.prefix_index_entries)
        reg.gauge("serve_queue_depth", "Requests waiting for admission"
                  ).set_function(lambda: self.queue_depth)
        reg.gauge("serve_running", "Requests in flight"
                  ).set_function(lambda: len(self.scheduler.running))
        reg.gauge("serve_outstanding_tokens", "Token-steps of work owed"
                  ).set_function(lambda: self.outstanding_tokens)
        if self.kv_store is not None:
            store, cs = self.kv_store, self.cache_state
            kvb = reg.gauge("kv_store_bytes",
                            "Fleet KV store payload bytes by tier",
                            labelnames=("tier",))
            kvb.labels(tier="host").set_function(
                lambda: store.host_bytes_used)
            kvb.labels(tier="disk").set_function(
                lambda: store.disk_bytes_used)
            kvh = reg.counter("kv_store_hits_total",
                              "Store block hits by serving tier",
                              labelnames=("tier",))
            kvh.labels(tier="host").set_function(
                lambda: store.counters["hits_host"])
            kvh.labels(tier="disk").set_function(
                lambda: store.counters["hits_disk"])
            kvt = reg.counter("kv_store_hit_tokens_total",
                              "Prompt tokens admitted from the store",
                              labelnames=("tier",))
            kvt.labels(tier="host").set_function(
                lambda: cs.store_hit_tokens_host)
            kvt.labels(tier="disk").set_function(
                lambda: cs.store_hit_tokens_disk)
            kve = reg.counter("kv_store_evictions_total",
                              "Store entries evicted by tier",
                              labelnames=("tier",))
            kve.labels(tier="host").set_function(
                lambda: store.counters["evictions_host"])
            kve.labels(tier="disk").set_function(
                lambda: store.counters["evictions_disk"])
            reg.counter("kv_store_puts_total",
                        "Blocks published into the store"
                        ).set_function(lambda: store.counters["puts"])
            reg.counter("kv_store_spills_total",
                        "Evicted device blocks demoted into the store"
                        ).set_function(lambda: cs.n_store_spills)
            reg.counter("kv_store_migrated_tails_total",
                        "Migrated raw tail blocks admitted"
                        ).set_function(
                            lambda: self.scheduler.n_migrated_tail_fills)
        if self.spec_decoder is not None:
            reg.counter("serve_spec_drafted_total", "Draft tokens proposed"
                        ).set_function(lambda: self.stats["spec_drafted"])
            reg.counter("serve_spec_accepted_total", "Draft tokens accepted"
                        ).set_function(lambda: self.stats["spec_accepted"])
            reg.gauge("serve_spec_accept_rate",
                      "Accepted / drafted (cumulative)").set_function(
                          lambda: self.stats["spec_accepted"]
                          / max(1, int(self.stats["spec_drafted"])))

    def reset_stats(self) -> None:
        """Zero counters/clock between a warm-up run and a timed run. The
        engine must be drained (no waiting/running requests); the device
        pools keep stale KV but lengths masking means it is never read."""
        assert not self.scheduler.has_work(), "reset_stats on a busy engine"
        self._iters = 0
        self._t0 = None
        self.scheduler.n_preemptions = 0
        self.scheduler.n_admissions = 0
        self.scheduler.prefix_hit_tokens = 0
        self.scheduler.prompt_tokens = 0
        for k in self.scheduler.terminal_counts:
            self.scheduler.terminal_counts[k] = 0
        self.cache_state.n_prefix_evictions = 0
        self.cache_state.n_store_spills = 0
        self.cache_state.n_store_declined = 0
        self.cache_state.store_hit_tokens_host = 0
        self.cache_state.store_hit_tokens_disk = 0
        self.scheduler.n_migrated_tail_fills = 0
        self.scheduler.n_migration_declined = 0
        if self.kv_store is not None and self._owns_store:
            # A shared (front-end-owned) store keeps its fleet counters;
            # a private one resets with the engine.
            self.kv_store.reset_stats()
        self.wall_elapsed = 0.0
        self._deadline_margins = []
        if self.spec_decoder is not None:
            self.spec_decoder.reset_stats()
        self.tracer.reset()
        self.ledger.reset()
        self.serve_ts = []
        for k in self.stats:
            self.stats[k] = 0.0 if isinstance(self.stats[k], float) else 0

    # -- one engine iteration ----------------------------------------------

    def step(self) -> List[Request]:
        """Run one scheduler iteration. Returns the requests that reached
        a terminal state this iteration: finished streams, plus anything
        the deadline sweep retired at the boundary (their blocks are
        already back in the pool)."""
        if not self._metrics_on:
            return self._step_impl()
        t0 = time.perf_counter()
        try:
            return self._step_impl()
        finally:
            self._m_step_seconds.observe(time.perf_counter() - t0)

    def _step_impl(self) -> List[Request]:
        self._iters += 1
        with self.ledger.track("host_sched"):
            terminal = self._expire_deadlines()
            kind, reqs = self.scheduler.schedule()
        if kind == "idle":
            self.stats["idle_iters"] += 1
            return terminal
        if kind == "prefill":
            terminal += self._forward(reqs, prefill=True)
            self.stats["prefill_iters"] += 1
        elif self.spec_decoder is not None:
            terminal += self._spec_decode()
            self.stats["decode_iters"] += 1
        else:
            reqs = self.scheduler.ensure_decode_blocks()
            if not reqs:          # everything preempted itself back out
                return terminal
            terminal += self._forward(reqs, prefill=False)
            self.stats["decode_iters"] += 1
        occ = self.cache_state.pool.occupancy
        self.stats["occupancy_sum"] += occ
        self.stats["occupancy_samples"] += 1
        self.stats["occupancy_max"] = max(self.stats["occupancy_max"], occ)
        return terminal

    def _expire_deadlines(self) -> List[Request]:
        """The iteration-boundary deadline sweep (scheduler.expire) plus
        the engine-side bookkeeping a terminal request needs. Skips the
        clock read entirely when nothing carries a deadline, so runs
        without deadlines are untouched."""
        s = self.scheduler
        if (all(r.deadline is None for r in s.waiting)
                and all(r.deadline is None for r in s.running)):
            return []
        now = self._now()
        expired = s.expire(now)
        for r in expired:
            r.finished_at = now
            if self.spec_decoder is not None:
                self.spec_decoder.forget(r)
            self.stats["deadline_exceeded"] += 1
            self._observe_deadline(r, now)
        return expired

    def _observe_deadline(self, r: Request, now: float) -> None:
        if r.deadline is not None:
            self._deadline_margins.append(now - r.deadline)

    def cancel(self, rid: int) -> bool:
        """Cancel a queued or in-flight request NOW: terminal status
        ``cancelled``, slot and paged KV blocks (speculative tails
        included) back in the pool before this call returns — not at the
        next drain. False if ``rid`` is not queued or in flight here.
        The request never appears in a later ``step()`` return; callers
        doing conservation accounting count the cancel themselves."""
        req = self.scheduler.cancel(rid)
        if req is None:
            return False
        req.finished_at = self._now()
        if self.spec_decoder is not None:
            self.spec_decoder.forget(req)
        self.stats["cancelled"] += 1
        return True

    def _forward(self, reqs: List[Request], *, prefill: bool) -> List[Request]:
        slots = self.max_batch
        cs = self.cache_state
        # Only the stepped rows carry real tables: other running
        # requests' rows are nulled so this pass cannot touch their
        # blocks (a mid-prefill row in a decode pass would otherwise
        # take a length-0 write into its first real block).
        tables = np.zeros_like(cs.tables)
        lengths = np.zeros((slots,), np.int32)
        offsets = np.zeros((slots,), np.int32)
        hist_blocks = 0
        if prefill:
            width = _bucket_pow2(max(r.prefill_chunk for r in reqs))
            width = min(width, cs.capacity_tokens())
            ids = np.zeros((slots, width), np.int32)
            max_cursor = 0
            for r in reqs:
                seq = r.prompt + r.generated
                cur, n = r.prefill_cursor, r.prefill_chunk
                ids[r.slot, :n] = seq[cur:cur + n]
                tables[r.slot] = cs.tables[r.slot]
                lengths[r.slot] = cur + n
                offsets[r.slot] = cur
                max_cursor = max(max_cursor, cur)
                self.stats["prefill_tokens"] += n
                self.stats["prefill_chunks"] += 1
            if max_cursor > 0:
                # Static history width (blocks), pow2-bucketed so chunk
                # resumes at nearby depths share a compile. 0 keeps the
                # original no-history prefill computation bit-for-bit.
                hist_blocks = min(
                    _bucket_pow2(cs.blocks_for(max_cursor), lo=1),
                    cs.max_blocks,
                )
        else:
            ids = np.zeros((slots, 1), np.int32)
            for r in reqs:
                ids[r.slot, 0] = (r.prompt + r.generated)[-1]
                tables[r.slot] = cs.tables[r.slot]
                lengths[r.slot] = r.cached_tokens()
        temps = np.zeros((slots,), np.float32)
        topks = np.zeros((slots,), np.int32)
        topps = np.ones((slots,), np.float32)
        keys = np.zeros((slots, 2), np.uint32)
        steps = np.zeros((slots,), np.int32)
        for r in reqs:
            temps[r.slot] = r.sampling.temperature
            topks[r.slot] = r.sampling.top_k
            topps[r.slot] = r.sampling.top_p
            keys[r.slot] = r.key()
            steps[r.slot] = len(r.generated)   # index of the draw made now
            if r.sampling.top_k > self._k_cap:
                self._k_cap = r.sampling.top_k

        with self.ledger.track("dispatch"):
            self.device_cache, tokens = self._step_jit(
                self.params, self.device_cache,
                jnp.asarray(tables), jnp.asarray(lengths),
                jnp.asarray(offsets), jnp.asarray(ids),
                jnp.asarray(temps), jnp.asarray(topks), jnp.asarray(topps),
                jnp.asarray(keys),
                jnp.asarray(steps), k_cap=self._k_cap, prefill=prefill,
                hist_blocks=hist_blocks,
            )
            tokens = np.asarray(tokens)   # host read = dispatch sync

        now = self._now()
        finished: List[Request] = []
        for r in reqs:
            if prefill:
                r.prefill_cursor += r.prefill_chunk
                cs.lengths[r.slot] = r.prefill_cursor
                self.tracer.emit(r.rid, "prefill_chunk", now,
                                 tokens=r.prefill_chunk,
                                 cursor=r.prefill_cursor)
                if self.prefix_cache:
                    self._register_prefix_blocks(r)
                if r.prefilling():
                    # Mid-prefill chunk: the sampled draw is discarded —
                    # the final chunk redraws at the same (seed, token
                    # index), so the stream matches an unchunked pass.
                    continue
            tok = int(tokens[r.slot])
            if r.token_times:
                self._m_tpot.observe(max(0.0, now - r.token_times[-1]))
            r.generated.append(tok)
            r.token_times.append(now)
            self.stats["generated_tokens"] += 1
            # Cache now holds everything fed this pass (not the new token).
            cs.lengths[r.slot] = r.context_len() - 1
            if r.first_token_at is None:
                r.first_token_at = now
                self._m_ttft.observe(max(0.0, now - r.arrival_time))
                self.tracer.emit(r.rid, "first_token", now)
            if (r.eos_id is not None and tok == r.eos_id) or (
                len(r.generated) >= r.max_new_tokens
            ):
                r.finished_at = now
                self.scheduler.retire(r)
                self.stats["finished"] += 1
                self._observe_deadline(r, now)
                finished.append(r)
        return finished

    def _spec_decode(self) -> List[Request]:
        """One speculative decode iteration: propose per-request drafts,
        pre-grow blocks for the worst-case window, verify all K+1
        positions in ONE target forward (the chunked-prefill branch at
        each row's cached offset), then emit the accepted prefix plus
        the target's correction/bonus token and rewind — host lengths
        roll back to the accept point and trailing blocks return to the
        pool the same iteration. Greedy rows emit the target argmax
        chain, so their streams bit-match non-speculative decode."""
        sd = self.spec_decoder
        cs = self.cache_state
        reqs = [r for r in self.scheduler.running
                if r.status == "running" and not r.prefilling()]
        if not reqs:
            return []
        drafts = sd.propose(reqs)
        window = {r.rid: len(drafts.get(r.rid, [])) + 1 for r in reqs}
        if all(n == 1 for n in window.values()):
            # Nothing drafted anywhere: plain single-token decode.
            reqs = self.scheduler.ensure_decode_blocks()
            if not reqs:
                return []
            return self._forward(reqs, prefill=False)
        reqs = self.scheduler.ensure_spec_blocks(reqs, window)
        if not reqs:              # everything preempted itself back out
            return []
        max_m = max(window[r.rid] - 1 for r in reqs)
        if max_m == 0:            # the drafted rows were all preempted
            return self._forward(reqs, prefill=False)

        slots = self.max_batch
        width = min(_bucket_pow2(max_m + 1, lo=2), cs.capacity_tokens())
        tables = np.zeros_like(cs.tables)
        lengths = np.zeros((slots,), np.int32)
        offsets = np.zeros((slots,), np.int32)
        ids = np.zeros((slots, width), np.int32)
        dlens = np.zeros((slots,), np.int32)
        temps = np.zeros((slots,), np.float32)
        topks = np.zeros((slots,), np.int32)
        topps = np.ones((slots,), np.float32)
        keys = np.zeros((slots, 2), np.uint32)
        steps = np.zeros((slots,), np.int32)
        max_off = 0
        for r in reqs:
            d = drafts.get(r.rid, [])
            cached = r.cached_tokens()
            seq = r.prompt + r.generated
            ids[r.slot, 0] = seq[-1]
            ids[r.slot, 1:1 + len(d)] = d
            tables[r.slot] = cs.tables[r.slot]
            offsets[r.slot] = cached
            lengths[r.slot] = cached + len(d) + 1
            dlens[r.slot] = len(d)
            temps[r.slot] = r.sampling.temperature
            topks[r.slot] = r.sampling.top_k
            topps[r.slot] = r.sampling.top_p
            keys[r.slot] = r.key()
            steps[r.slot] = len(r.generated)
            max_off = max(max_off, cached)
            if r.sampling.top_k > self._k_cap:
                self._k_cap = r.sampling.top_k
        # The window rides the chunked-prefill branch: cached context is
        # the pooled history (cached >= 1 always in decode).
        hist_blocks = min(
            _bucket_pow2(cs.blocks_for(max_off), lo=1), cs.max_blocks)

        with self.ledger.track("dispatch"):
            self.device_cache, emitted, n_acc = self._verify_jit(
                self.params, self.device_cache,
                jnp.asarray(tables), jnp.asarray(lengths),
                jnp.asarray(offsets), jnp.asarray(ids), jnp.asarray(dlens),
                jnp.asarray(temps), jnp.asarray(topks), jnp.asarray(topps),
                jnp.asarray(keys), jnp.asarray(steps),
                k_cap=self._k_cap, hist_blocks=hist_blocks,
            )
            emitted = np.asarray(emitted)
            n_acc = np.asarray(n_acc)

        now = self._now()
        finished: List[Request] = []
        for r in reqs:
            m = int(dlens[r.slot])
            j = int(n_acc[r.slot])
            sd.observe(r, m, j)
            if m > 0:
                self.tracer.emit(r.rid, "spec_window", now, k=m, accepted=j)
            self.stats["spec_steps"] += 1
            self.stats["spec_drafted"] += m
            self.stats["spec_accepted"] += j
            done = False
            for tok in emitted[r.slot, :j + 1]:
                tok = int(tok)
                r.generated.append(tok)
                if r.token_times:
                    self._m_tpot.observe(max(0.0, now - r.token_times[-1]))
                r.token_times.append(now)
                self.stats["generated_tokens"] += 1
                if r.first_token_at is None:
                    r.first_token_at = now
                    self._m_ttft.observe(max(0.0, now - r.arrival_time))
                    self.tracer.emit(r.rid, "first_token", now)
                if (r.eos_id is not None and tok == r.eos_id) or (
                    len(r.generated) >= r.max_new_tokens
                ):
                    done = True
                    break     # tokens past EOS are never emitted
            # Host rewind: cache holds everything up to the accept point
            # (write-ahead past it is masked garbage the shrink reclaims).
            cs.lengths[r.slot] = r.context_len() - 1
            if done:
                r.finished_at = now
                sd.forget(r)
                self.scheduler.retire(r)
                self.stats["finished"] += 1
                self._observe_deadline(r, now)
                finished.append(r)
            else:
                self.scheduler.shrink_spec_blocks(r)
        return finished

    def _register_prefix_blocks(self, r: Request) -> None:
        """Publish the request's newly completed full PROMPT blocks in
        the prefix index (shared-prefix blocks are already there; the
        register is a no-op on an existing digest)."""
        cs = self.cache_state
        bsz = cs.block_size
        done = min(r.prefill_cursor, len(r.prompt)) // bsz
        if done <= r._blocks_registered:
            return
        if r._prompt_digests is None:
            r._prompt_digests = cs.block_digests(r.prompt)
        blocks = cs.slot_blocks(r.slot)
        for i in range(r._blocks_registered, done):
            cs.prefix_register(r._prompt_digests[i], blocks[i])
            if self.kv_store is not None:
                # Write-through to the fleet tier: a block computed on
                # ANY replica is addressable fleet-wide the moment it is
                # published, not only when local eviction spills it.
                self._store_put_block(r._prompt_digests[i], blocks[i])
        r._blocks_registered = done

    # -- fleet KV store: device block I/O + migration ----------------------

    def _build_pricer(self, link_gbps: float) -> MigrationPricer:
        from tpu_trainer.utils.logging import (
            device_peak_flops,
            flops_per_token,
        )

        try:
            peak = float(device_peak_flops())
        except Exception:
            peak = 1e12
        # flops_per_token counts fwd+bwd (6N + attn); the recompute a
        # migration avoids is one forward pass — a third of that.
        fwd = flops_per_token(self.config) / 3.0
        return MigrationPricer(
            flops_per_token=fwd, device_flops=peak,
            link_bytes_per_s=float(link_gbps) * 1e9)

    def _pool_leaves(self) -> Tuple[List, List[int], object]:
        """Flatten the device cache; memoize which leaf positions are
        block pools (the structure is static — steps replace values, not
        shape). Returns (all leaves, pool leaf indices, treedef)."""
        flat, treedef = jax.tree_util.tree_flatten_with_path(
            self.device_cache)
        if self._pool_leaf_idx is None:
            self._pool_leaf_idx = [
                i for i, (path, _) in enumerate(flat)
                if getattr(path[-1], "key", None) in _POOL_LEAF_KEYS]
        return [leaf for _, leaf in flat], self._pool_leaf_idx, treedef

    @staticmethod
    def _block_index(leaf, block_id: int) -> tuple:
        """Index tuple selecting one block from a pool leaf. Per-layer
        pools are rank 4 ``[nblk, bsz, kvh, d|nbq]``; the scanned model
        stacks layers in front (rank 5, block axis 1)."""
        return (slice(None),) * (leaf.ndim - 4) + (block_id,)

    def read_block(self, block_id: int) -> List[np.ndarray]:
        """One block's K/V payload as host arrays, one per pool leaf in
        tree-flatten order — the store/wire entry format. Engines built
        from the same config flatten identically, so entries round-trip
        across the fleet."""
        leaves, idx, _ = self._pool_leaves()
        return [np.asarray(leaves[i][self._block_index(leaves[i], block_id)])
                for i in idx]

    def write_block(self, block_id: int, payload: List[np.ndarray]) -> bool:
        """Write a store/migration entry into device block ``block_id``.
        False (device untouched) on any layout mismatch — a store shared
        across differently configured engines degrades to recompute
        instead of corrupting a pool."""
        leaves, idx, treedef = self._pool_leaves()
        if len(payload) != len(idx):
            return False
        for j, arr in zip(idx, payload):
            cur = leaves[j]
            ax = cur.ndim - 4
            want = tuple(cur.shape[:ax]) + tuple(cur.shape[ax + 1:])
            if (tuple(arr.shape) != want
                    or np.dtype(arr.dtype) != np.dtype(cur.dtype)):
                return False
        for j, arr in zip(idx, payload):
            leaves[j] = leaves[j].at[
                self._block_index(leaves[j], block_id)].set(jnp.asarray(arr))
        self.device_cache = jax.tree_util.tree_unflatten(treedef, leaves)
        return True

    def _store_put_block(self, digest: bytes, block_id: int) -> bool:
        """Publish one device block into the fleet store (idempotent per
        digest). Doubles as the cache's eviction spill hook."""
        if self.kv_store is None or self.device_cache is None:
            return False
        if self.kv_store.has(digest):
            return False
        return self.kv_store.put(digest, self.read_block(block_id))

    def _store_fill_block(self, digest: bytes, block_id: int):
        """The cache's store fall-through hook: fetch ``digest`` and fill
        a freshly allocated device block. Returns the serving tier
        ("host"/"disk") or None on miss/mismatch."""
        got = self.kv_store.get(digest)
        if got is None:
            return None
        tier, payload = got
        return tier if self.write_block(block_id, payload) else None

    def set_role(self, role: Optional[str]) -> None:
        """Assign this replica's disaggregation role. ``"prefill"``
        disables decode scheduling: requests run to the end of prefill
        (sampling their first token) and then idle until the front-end
        extracts them for migration. ``"decode"``/None is a full
        engine."""
        if role not in (None, "prefill", "decode"):
            raise ValueError(f"role={role!r} (prefill | decode | None)")
        self.role = role
        self.scheduler.decode_enabled = role != "prefill"

    def migratable_rids(self) -> List[int]:
        """Requests a prefill-role replica has carried as far as it can:
        prefill complete and the first token sampled — exactly the state
        a decode replica needs to continue the stream."""
        return [r.rid for r in self.scheduler.running
                if r.status == "running" and not r.prefilling()
                and r.generated]

    def extract_request(self, rid: int):
        """Migration harvest + handoff: publish the request's full
        prompt blocks to the fleet store (digest-addressed), read its
        sub-block tail raw, then strip it out of the scheduler in
        fresh-waiting state. Returns ``(request, payload)`` with payload
        ``{"tail_ntok", "leaves"}``, or None if ``rid`` is not in a
        migratable state. Re-admission elsewhere matches the full blocks
        through the store, fills the tail raw, and resumes sampling at
        the same (seed, token_index) — bit-identical to never moving."""
        req = next(
            (r for r in self.scheduler.running if r.rid == rid), None)
        if req is None or req.prefilling() or not req.generated:
            return None
        cs = self.cache_state
        payload = {"tail_ntok": 0, "leaves": None}
        if self.kv_store is not None:
            if req._prompt_digests is None:
                req._prompt_digests = cs.block_digests(req.prompt)
            blocks = cs.slot_blocks(req.slot)
            full = len(req.prompt) // cs.block_size
            for i in range(min(full, len(blocks))):
                self._store_put_block(req._prompt_digests[i], blocks[i])
            tail = len(req.prompt) - full * cs.block_size
            if tail and full < len(blocks):
                payload = {"tail_ntok": tail,
                           "leaves": self.read_block(blocks[full])}
        if self.spec_decoder is not None:
            self.spec_decoder.forget(req)
        self.scheduler.extract(req)
        return req, payload

    def _now(self) -> float:
        if self._t0 is None:
            self._t0 = self.clock()
        return self.clock() - self._t0

    # -- load signals (the multi-replica router's inputs; also summary
    # telemetry for single-engine runs) ------------------------------------

    @property
    def queue_depth(self) -> int:
        """Requests waiting for admission."""
        return self.scheduler.queue_depth

    @property
    def outstanding_tokens(self) -> int:
        """Token-steps of work still owed (waiting + running)."""
        return self.scheduler.outstanding_tokens

    def oldest_wait_age(self, now: Optional[float] = None) -> float:
        """How long (engine clock units) the longest-waiting queued
        request has been waiting; 0.0 with an empty queue."""
        arr = self.scheduler.oldest_waiting_arrival
        if arr is None:
            return 0.0
        return max(0.0, (self._now() if now is None else now) - arr)

    def export_requests(self, *, waiting_only: bool = False):
        """Drain this engine's requeueable request state (see
        ``Scheduler.export_requests``) — the failover / shrink-teardown
        path of the multi-replica front-end."""
        return self.scheduler.export_requests(waiting_only=waiting_only)

    # -- trace replay ------------------------------------------------------

    def run(
        self,
        requests: Sequence[Request],
        *,
        time_mode: str = "wall",
        max_iters: int = 10_000_000,
        profiler=None,
    ) -> List[Request]:
        """Replay an open-loop trace: each request joins the waiting queue
        when the clock passes its ``arrival_time``. ``time_mode="wall"``
        measures arrivals in seconds; ``"steps"`` measures them in engine
        iterations — fully deterministic, for tests and replay checks.
        Returns the finished requests in input order; requests that
        ended cancelled or past their deadline are dropped from the
        return (their terminal state lives on the Request objects the
        caller already holds, and in ``summary()``).

        ``profiler`` (utils.profiling.WindowedTrace or anything with a
        ``step(i) -> context`` method) wraps each engine iteration in a
        ``jax.profiler.StepTraceAnnotation`` while its window is open —
        the serve_bench ``--profile-trace`` hook. Every ``ts_interval``
        iterations the run appends a ``kind:"serve_ts"`` sample (ledger
        fractions + as-of-now gauges) to ``self.serve_ts``."""
        if time_mode not in ("wall", "steps"):
            raise ValueError(f"time_mode={time_mode!r}")
        pending = sorted(requests, key=lambda r: (r.arrival_time, r.rid))
        pending = list(pending)
        self._t0 = self.clock()
        t_start = self._t0
        done: List[Request] = []
        while pending or self.scheduler.has_work():
            now = (
                float(self._iters) if time_mode == "steps" else self._now()
            )
            while pending and pending[0].arrival_time <= now:
                self.scheduler.add(pending.pop(0))
            if not self.scheduler.has_work():
                with self.ledger.track("idle"):
                    if time_mode == "wall":
                        time.sleep(
                            min(1e-3,
                                max(0.0, pending[0].arrival_time - now))
                        )
                    else:
                        self._iters += 1  # idle tick advances the clock
                continue
            if profiler is None:
                done.extend(self.step())
            else:
                with profiler.step(self._iters):
                    done.extend(self.step())
            if self.ts_interval and self._iters % self.ts_interval == 0:
                self._emit_ts()
            if self._iters >= max_iters:
                raise RuntimeError(f"engine did not drain in {max_iters} iters")
        self.wall_elapsed = self.clock() - t_start
        self._emit_ts(final=True)
        by_rid = {r.rid: r for r in done if r.status == "finished"}
        return [by_rid[r.rid] for r in requests if r.rid in by_rid]

    def _emit_ts(self, final: bool = False) -> dict:
        """One ``kind:"serve_ts"`` time-series sample: the ledger's
        wall-clock attribution so far plus as-of-now load gauges. Routed
        through ``metric_logger`` (the MetricLogger JSONL/TB/wandb sinks)
        when one is attached; always kept on ``self.serve_ts``."""
        s = self.stats
        gauges = {
            "t": round(self._now(), 6),
            "iter": int(self._iters),
            "queue_depth": self.queue_depth,
            "running": len(self.scheduler.running),
            "outstanding_tokens": self.outstanding_tokens,
            "occupancy": round(float(self.cache_state.pool.occupancy), 4),
            "generated_tokens": int(s["generated_tokens"]),
            "prefix_hit_rate": round(
                self.scheduler.prefix_hit_tokens
                / max(1, self.scheduler.prompt_tokens), 4),
        }
        if self.spec_decoder is not None:
            gauges["spec_accept_rate"] = round(
                s["spec_accepted"] / max(1, int(s["spec_drafted"])), 4)
        rec = self.ledger.record(gauges, final=final)
        self.serve_ts.append(rec)
        if self.metric_logger is not None:
            self.metric_logger.log_record(rec)
        return rec

    def summary(self) -> Dict[str, float]:
        s = dict(self.stats)
        n = max(1, int(s.pop("occupancy_samples")))
        s["occupancy_mean"] = s.pop("occupancy_sum") / n
        s["preemptions"] = self.scheduler.n_preemptions
        s["iters"] = self._iters
        s["prompt_tokens"] = self.scheduler.prompt_tokens
        s["prefix_hit_tokens"] = self.scheduler.prefix_hit_tokens
        s["prefix_hit_rate"] = (
            self.scheduler.prefix_hit_tokens
            / max(1, self.scheduler.prompt_tokens)
        )
        s["prefix_evictions"] = self.cache_state.n_prefix_evictions
        if self.kv_store is not None:
            cs = self.cache_state
            s["store_hit_tokens_host"] = cs.store_hit_tokens_host
            s["store_hit_tokens_disk"] = cs.store_hit_tokens_disk
            s["store_hit_tokens"] = (
                cs.store_hit_tokens_host + cs.store_hit_tokens_disk)
            s["store_spills"] = cs.n_store_spills
            s["store_declined"] = cs.n_store_declined
            s["migrated_tail_fills"] = self.scheduler.n_migrated_tail_fills
            s["migration_declined"] = self.scheduler.n_migration_declined
            for k, v in self.kv_store.stats().items():
                s[f"kv_store_{k}"] = v
        s.update(self.cache_state.fragmentation())
        s.update(self.scheduler.pool_shard_stats())
        s["queue_depth"] = self.queue_depth
        s["outstanding_tokens"] = self.outstanding_tokens
        s["oldest_wait_s"] = (
            self.oldest_wait_age() if self.scheduler.waiting else 0.0)
        if self._deadline_margins:
            # Miss slack = how far past its deadline a deadline-carrying
            # request ended (0 for the ones that made it). Absent when
            # the run carried no deadlines, so analyze gates SKIP.
            margins = np.asarray(self._deadline_margins)
            slack = np.maximum(margins, 0.0)
            s["deadline_miss_rate"] = float(np.mean(margins > 0))
            s["deadline_miss_slack_p50"] = float(np.percentile(slack, 50))
            s["deadline_miss_slack_p99"] = float(np.percentile(slack, 99))
        if self.spec_decoder is not None:
            s["spec_accept_mean"] = (
                s["spec_accepted"] / max(1, int(s["spec_steps"])))
            s["spec_accept_rate"] = (
                s["spec_accepted"] / max(1, int(s["spec_drafted"])))
            s["spec_accept_hist"] = list(self.spec_decoder.accept_hist)
        else:
            for k in ("spec_steps", "spec_drafted", "spec_accepted"):
                s.pop(k)
        if getattr(self, "wall_elapsed", 0):
            s["wall_s"] = self.wall_elapsed
            s["tokens_per_s"] = s["generated_tokens"] / self.wall_elapsed
        return s


def _engine_step(
    config, params, cache, tables, lengths, offsets, ids,
    temps, topks, topps, keys, steps, *, k_cap: int, prefill: bool,
    hist_blocks: int,
) -> Tuple[dict, jax.Array]:
    """One jitted engine step: broadcast host scheduling state into the
    cache pytree, forward, gather each row's last real logit, sample.
    ``hist_blocks`` is the static chunked-prefill history width — the
    model is built per trace with it baked into the config, so each
    (width bucket, history bucket) pair compiles once."""

    def put(path, x):
        key = getattr(path[-1], "key", None)
        if key == "tables":
            return jnp.broadcast_to(tables, x.shape)
        if key == "lengths":
            return jnp.broadcast_to(lengths, x.shape)
        if key == "offsets":
            return jnp.broadcast_to(offsets, x.shape)
        return x

    model = GPT(dataclasses.replace(config, paged_hist_blocks=hist_blocks))
    cache = jax.tree_util.tree_map_with_path(put, cache)
    if config.paged_tp > 1:
        # Sharded replica: params live sharded on the mesh — gather them
        # to replicated here (an exact concat, no arithmetic) so the
        # dense compute below is bitwise the single-device compute, and
        # pin the output cache back to the pool layout so the scatter's
        # result never drifts off the committed sharding.
        from tpu_trainer.serving import sharding as tp_lib

        mesh = tp_lib.tp_mesh(config.paged_tp, config.paged_tp_devices)
        params = tp_lib.gather_params(params, mesh)
    (logits, _), vars_out = model.apply(
        {"params": params, "cache": cache}, ids, decode=True,
        mutable=["cache"],
    )
    if config.paged_tp > 1:
        vars_out = {"cache": tp_lib.constrain_cache(
            vars_out["cache"], mesh, config.kv_heads)}
    if prefill:
        last = jnp.take_along_axis(
            logits, jnp.maximum(lengths - offsets - 1, 0)[:, None, None],
            axis=1,
        )[:, 0]
    else:
        last = logits[:, 0]
    tokens = sample_tokens(
        last.astype(jnp.float32), temps, topks, topps, keys, steps,
        k_cap=k_cap,
    )
    return vars_out["cache"], tokens


@functools.lru_cache(maxsize=None)
def _jitted_engine_step(config):
    """Per-config memo of the jitted step. ``GPTConfig`` is frozen, so
    engines built with equal configs get the SAME jit object — and with
    it the same compile cache. Constructing a second identically-shaped
    engine (warm-up/timed pairs, A/B lanes, test matrices, the draft
    proposer reusing the target's step) then costs zero retraces.

    Device/mesh identity is part of the key: the config carries
    ``(paged_tp, paged_tp_devices)``, so two equal-shaped engines built
    for different device sets (or sharded vs single-device) never share
    a jit object — sharing one would dispatch the second engine's steps
    onto the first engine's devices."""
    return jax.jit(
        functools.partial(_engine_step, config),
        static_argnames=("k_cap", "prefill", "hist_blocks"),
    )


@functools.lru_cache(maxsize=None)
def _jitted_verify_step(config):
    """Same per-config sharing — and the same (paged_tp,
    paged_tp_devices) mesh-identity keying — for the speculative verify
    step."""
    return jax.jit(
        functools.partial(_verify_step, config),
        static_argnames=("k_cap", "hist_blocks"),
    )


def poisson_trace(
    n_requests: int,
    *,
    vocab_size: int,
    rate: float = 8.0,
    seed: int = 0,
    prompt_len_range: Tuple[int, int] = (8, 64),
    max_new_range: Tuple[int, int] = (8, 32),
    temperature: float = 1.0,
    top_k: int = 0,
    top_p: float = 1.0,
    eos_id: Optional[int] = None,
) -> List[Request]:
    """Synthetic open-loop trace: exponential inter-arrivals at ``rate``
    requests per time unit, uniform prompt/output lengths, one sampling
    seed per request — all from one ``seed``, so a trace is replayable
    bit-for-bit."""
    rs = np.random.RandomState(seed)
    arrivals = np.cumsum(rs.exponential(1.0 / rate, size=n_requests))
    out = []
    for i in range(n_requests):
        plen = int(rs.randint(prompt_len_range[0], prompt_len_range[1] + 1))
        mnew = int(rs.randint(max_new_range[0], max_new_range[1] + 1))
        prompt = rs.randint(1, vocab_size, size=plen).tolist()
        out.append(Request(
            rid=i,
            prompt=[int(t) for t in prompt],
            max_new_tokens=mnew,
            sampling=SamplingParams(
                temperature=temperature, top_k=top_k, top_p=top_p,
                seed=int(rs.randint(0, 2**31 - 1)),
            ),
            arrival_time=float(arrivals[i]),
            eos_id=eos_id,
        ))
    return out


def request_metrics(reqs: Sequence[Request]) -> Dict[str, List[float]]:
    """Latency series (same time axis the engine ran on): TTFT = first
    token minus arrival, one sample per request; TPOT = every individual
    inter-token gap (a.k.a. inter-token latency). Per-GAP samples are the
    point: a monolithic prefill landing mid-decode stalls every in-flight
    stream for the whole prompt, which a per-request MEAN averages away —
    the p99 of the gaps is where that tail lives (and what chunked
    prefill is for). Falls back to the mean-gap estimate for requests
    recorded without per-token timestamps.

    ``queue_wait`` = first admission minus arrival (one sample per
    admitted request, preemption re-admissions excluded) — the phase
    TTFT hides: a request can clear admission instantly and still pay a
    long prefill, or sit queued behind a full pool. Comes from the span
    layer's ``admitted_at`` stamp, so it survives the RPC wire."""
    ttft, tpot, queue_wait = [], [], []
    for r in reqs:
        if r.admitted_at is not None:
            queue_wait.append(max(0.0, r.admitted_at - r.arrival_time))
        if r.first_token_at is None:
            continue
        ttft.append(r.first_token_at - r.arrival_time)
        if len(r.token_times) >= 2:
            tpot.extend(
                b - a for a, b in zip(r.token_times, r.token_times[1:])
            )
        elif not r.token_times:
            n_rest = len(r.generated) - 1
            if n_rest > 0 and r.finished_at is not None:
                tpot.append((r.finished_at - r.first_token_at) / n_rest)
    return {"ttft": ttft, "tpot": tpot, "queue_wait": queue_wait}


def _main() -> int:
    import argparse
    import json

    p = argparse.ArgumentParser(
        description="Replay a seeded Poisson trace through the serving "
        "engine on a synthetic checkpoint."
    )
    p.add_argument("--requests", type=int, default=32)
    p.add_argument("--rate", type=float, default=8.0)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--max-batch", type=int, default=8)
    p.add_argument("--block-size", type=int, default=16)
    p.add_argument("--num-blocks", type=int, default=0,
                   help="KV pool blocks (0 = size for max_batch full contexts)")
    p.add_argument("--kv-int8", action="store_true")
    p.add_argument("--prefill-chunk", type=int, default=0,
                   help="chunked-prefill token budget per iteration "
                        "(0 = whole-prompt prefill)")
    p.add_argument("--prefix-cache", action="store_true",
                   help="copy-on-write prefix sharing in the block pool")
    p.add_argument("--attention", default="auto",
                   choices=("auto", "reference", "kernel"))
    p.add_argument("--temperature", type=float, default=1.0)
    p.add_argument("--top-k", type=int, default=0)
    p.add_argument("--top-p", type=float, default=1.0,
                   help="nucleus sampling mass (1.0 = off)")
    p.add_argument("--spec", default="off",
                   choices=("off", "ngram", "draft"),
                   help="speculative decoding proposer")
    p.add_argument("--spec-k", type=int, default=4,
                   help="max draft tokens per verify step")
    p.add_argument("--spec-draft-layers", type=int, default=1,
                   help="target layers sliced into the draft model "
                        "(--spec draft)")
    p.add_argument("--time-mode", default="wall", choices=("wall", "steps"))
    p.add_argument("--vocab", type=int, default=512)
    p.add_argument("--hidden", type=int, default=128)
    p.add_argument("--layers", type=int, default=2)
    p.add_argument("--heads", type=int, default=4)
    p.add_argument("--max-seq-len", type=int, default=256)
    args = p.parse_args()

    config = GPTConfig(
        vocab_size=args.vocab, hidden_size=args.hidden,
        num_layers=args.layers, num_heads=args.heads,
        max_seq_len=args.max_seq_len, dropout=0.0, attention_dropout=0.0,
        dtype="float32", param_dtype="float32",
    )
    model = GPT(config)
    params = model.init(
        jax.random.PRNGKey(args.seed), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    draft_params = draft_config = None
    if args.spec == "draft":
        draft_params, draft_config = draft_from_target(
            params, config, args.spec_draft_layers)
    engine = ServingEngine(
        params, config, max_batch=args.max_batch,
        block_size=args.block_size,
        num_blocks=args.num_blocks or None,
        kv_int8=args.kv_int8, attention=args.attention,
        prefill_chunk_tokens=args.prefill_chunk or None,
        prefix_cache=args.prefix_cache,
        spec=args.spec, spec_k=args.spec_k,
        draft_params=draft_params, draft_config=draft_config,
    )
    trace = poisson_trace(
        args.requests, vocab_size=args.vocab, rate=args.rate,
        seed=args.seed, temperature=args.temperature, top_k=args.top_k,
        top_p=args.top_p,
    )
    finished = engine.run(trace, time_mode=args.time_mode)
    summary = engine.summary()
    lat = request_metrics(finished)
    for name, series in lat.items():
        if series:
            summary[f"{name}_p50"] = float(np.percentile(series, 50))
            summary[f"{name}_p99"] = float(np.percentile(series, 99))
    print(json.dumps({k: round(v, 6) if isinstance(v, float) else v
                      for k, v in sorted(summary.items())}, indent=2))
    return 0


if __name__ == "__main__":
    raise SystemExit(_main())
