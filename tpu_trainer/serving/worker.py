"""One serving replica as its own OS process: ``python -m
tpu_trainer.serving.worker`` runs a single ``ServingEngine`` behind the
length-prefixed JSON RPC loop defined in ``serving/remote.py``.

The worker is a pure **RPC reactor** — the engine advances ONLY inside
a handler, never on its own schedule. That one design choice buys the
two properties the cross-process front-end needs:

- **Determinism**: the front-end drives every engine step and ships its
  own clock value (``now``) with each step RPC; the worker's engine is
  built with a captured clock (``clock=lambda: last now received``,
  zero epoch), so in ``steps`` mode every timestamp in the fleet is a
  front-end iteration number — one clock domain, bit-reproducible.
- **Exact load snapshots**: worker state between RPCs is frozen, so the
  ``load`` dict attached to every response (queue depth, outstanding
  tokens, oldest waiting ARRIVAL — age is computed front-end-side) is
  correct until the front-end's next call, with zero polling.

Token streams cross the wire as **deltas**: the worker tracks how many
generated tokens each request has already reported and sends only the
new suffix (plus timestamps and terminal state) per step — the
front-end applies them to its own mirror ``Request`` objects.

Liveness: a ``utils/flight_recorder`` heartbeat is beaten on every loop
wakeup (idle ``select`` timeouts included, throttled), so a healthy but
idle worker stays visibly alive while a wedged handler flatlines within
a second — the same signal the elastic trainer uses for hung hosts.

A torn or non-JSON frame poisons only the CONNECTION, not the process:
the worker closes that socket and goes back to ``accept``, so a
reconnecting front-end finds clean state and live requests survive.
"""

from __future__ import annotations

import argparse
import json
import os
import select
import socket
import sys
from typing import Dict, List, Optional

from tpu_trainer.serving.remote import (
    MAX_ATTACHED_FRAMES,
    FrameError,
    decode_kv_block,
    encode_kv_block,
    load_params_npz,
    recv_binary_frame,
    recv_frame,
    request_from_wire,
    request_to_wire,
    send_binary_frame,
    send_frame,
)
from tpu_trainer.serving.scheduler import Request, TERMINAL_STATES
from tpu_trainer.utils.flight_recorder import HeartbeatWriter


def _jsonable(x):
    """Engine summaries carry numpy scalars; JSON does not."""
    if isinstance(x, dict):
        return {k: _jsonable(v) for k, v in x.items()}
    if isinstance(x, (list, tuple)):
        return [_jsonable(v) for v in x]
    if hasattr(x, "item") and not isinstance(x, (str, bytes)):
        return x.item()
    return x


class WorkerServer:
    """The RPC reactor around one ``ServingEngine``."""

    def __init__(self, spec: dict, *, worker_id: int = 0,
                 heartbeat_dir: Optional[str] = None):
        self.spec = spec
        self.worker_id = worker_id
        self._now_value = 0.0
        self._steps = 0
        self._shutdown = False
        self._hb = (HeartbeatWriter(heartbeat_dir, host=worker_id,
                                    min_interval_s=0.2)
                    if heartbeat_dir else None)
        self._reqs: Dict[int, Request] = {}
        self._sent: Dict[int, int] = {}    # generated tokens already reported
        self.engine = self._build_engine()

    def _build_engine(self):
        # Imported here, not at module top: the heavy jax stack loads in
        # the worker process only, and only once argument parsing and
        # socket binding have already succeeded.
        import jax

        # Adopt the front-end process's PRNG scheme (recorded in the
        # spec by WorkerSupervisor): partitionable threefry changes
        # sampled bit streams, and cross-process bit-identity requires
        # every engine in the fleet to draw from the same one.
        jax_cfg = self.spec.get("jax", {})
        if "threefry_partitionable" in jax_cfg:
            jax.config.update("jax_threefry_partitionable",
                              bool(jax_cfg["threefry_partitionable"]))

        from tpu_trainer.models.config import GPTConfig
        from tpu_trainer.obs.metrics import MetricsRegistry
        from tpu_trainer.serving.engine import ServingEngine

        if self.spec.get("params_shards"):
            # Shard-streaming launch: params arrive as a host_shards
            # export (one ~P/world file per worker on the wire; a
            # shared-filesystem worker stitches the full tree from all
            # of them here, then the engine re-commits it to its own
            # mesh). The full-npz path below stays the single-device
            # fallback.
            from tpu_trainer.utils.checkpoint import load_param_shards

            params = load_param_shards(self.spec["params_shards"])
        else:
            params = load_params_npz(self.spec["params_npz"])
        config = GPTConfig(**self.spec["config"])
        kw = dict(self.spec.get("engine", {}))
        dsets = self.spec.get("device_sets")
        if dsets:
            # This worker's mesh device set: disjoint meshes over one
            # host's visible devices, assigned round-robin by worker id.
            kw["mesh_devices"] = tuple(
                int(d) for d in dsets[self.worker_id % len(dsets)])
        # Every worker engine gets a live registry: the front-end pulls
        # snapshots over the ``metrics`` verb and merges them label-wise
        # (replica=N) into its own registry. Single-threaded here — the
        # reactor owns both the engine and the scrape.
        eng = ServingEngine(params, config, clock=lambda: self._now_value,
                            registry=MetricsRegistry(), **kw)
        eng._t0 = 0.0   # front-end clock domain: timestamps ARE its times
        return eng

    def _beat(self) -> None:
        if self._hb is not None:
            self._hb.beat(self._steps)

    # -- load snapshot (see module docstring: exact between our RPCs) ------

    def _load(self) -> dict:
        eng = self.engine
        arr = eng.scheduler.oldest_waiting_arrival
        d = {
            "queue_depth": int(eng.queue_depth),
            "outstanding_tokens": int(eng.outstanding_tokens),
            "has_work": bool(eng.scheduler.has_work()),
            "oldest_arrival": None if arr is None else float(arr),
            "generated_tokens": int(eng.stats["generated_tokens"]),
            "prefix_hit_tokens": int(eng.scheduler.prefix_hit_tokens),
            "prompt_tokens": int(eng.scheduler.prompt_tokens),
            "n_preemptions": int(eng.scheduler.n_preemptions),
            "store_hit_tokens_host": int(
                eng.cache_state.store_hit_tokens_host),
            "store_hit_tokens_disk": int(
                eng.cache_state.store_hit_tokens_disk),
        }
        if eng.kv_store is not None:
            # Newly stored digests since the last reply — the front-end
            # catalogs them (digest -> holder) with zero extra RPCs.
            new = eng.kv_store.drain_new_digests()
            if new:
                d["kv_new"] = [dg.hex() for dg in new]
        if eng.role == "prefill":
            d["migratable"] = eng.migratable_rids()
        return d

    # -- handlers ----------------------------------------------------------

    def _delta(self, req: Request) -> dict:
        sent = self._sent[req.rid]
        return {
            "rid": req.rid,
            "gen": req.generated[sent:],
            "times": [float(t) for t in req.token_times[sent:]],
            "first": req.first_token_at,
            "status": req.status,
            "done": req.status in TERMINAL_STATES,
            "finished_at": req.finished_at,
            "preempt": req.preemptions,
            "hit": req.prefix_hit_tokens,
            "spec": [req.spec_drafted, req.spec_accepted, req.spec_steps],
        }

    def handle(self, msg: dict) -> dict:
        method = msg.get("method")
        if method == "hello":
            return {"block_size": int(self.engine.cache_state.block_size),
                    "pid": os.getpid(), "worker_id": self.worker_id,
                    "load": self._load()}
        if method == "ping":
            return {}
        if method == "submit":
            req = request_from_wire(msg["req"])
            # Front-door trace context (submitted/routed events) rides
            # the submit payload so this engine's tracer holds the rid's
            # FULL timeline — ingested non-pending, so the events are
            # never echoed back to the side that already has them.
            ctx = msg.get("trace")
            if ctx:
                self.engine.tracer.ingest(ctx)
            mig = msg.get("mig")
            if mig is not None:
                # Migrated admission: full blocks are already in our
                # store (kv_put'd by the front-end); the raw tail rides
                # the attached binary frame. Admission prices the tail
                # and every store fill against recompute per block.
                leaves = None
                frames = msg.get("_frames") or ()
                if frames:
                    leaves = decode_kv_block(frames[0])
                req._kv_migration = {
                    "tail_ntok": int(mig.get("tail_ntok", 0)),
                    "leaves": leaves}
            self.engine.scheduler.add(req)
            self._reqs[req.rid] = req
            self._sent[req.rid] = len(req.generated)
            return {"load": self._load()}
        if method == "step":
            self._now_value = float(msg.get("now", self._now_value))
            self.engine.step()
            self._steps += 1
            deltas: List[dict] = []
            for rid, req in list(self._reqs.items()):
                if len(req.generated) > self._sent[rid] or (
                        req.status in TERMINAL_STATES):
                    deltas.append(self._delta(req))
                    self._sent[rid] = len(req.generated)
                    if req.status in TERMINAL_STATES:
                        del self._reqs[rid]
                        del self._sent[rid]
            return {"deltas": deltas, "load": self._load()}
        if method == "cancel":
            # Terminal on the spot: the engine frees the request's slot
            # and blocks before this response is framed, and the request
            # never appears in a later step delta — the front-end mirror
            # applies the delta returned HERE instead.
            self._now_value = float(msg.get("now", self._now_value))
            rid = int(msg["rid"])
            ok = self.engine.cancel(rid)
            delta = None
            if ok and rid in self._reqs:
                req = self._reqs.pop(rid)
                delta = self._delta(req)
                del self._sent[rid]
            return {"cancelled": bool(ok), "delta": delta,
                    "load": self._load()}
        if method == "export":
            reqs = self.engine.export_requests(
                waiting_only=bool(msg.get("waiting_only", False)))
            for r in reqs:
                self._reqs.pop(r.rid, None)
                self._sent.pop(r.rid, None)
            return {"requests": [request_to_wire(r) for r in reqs],
                    "load": self._load()}
        if method == "kv_put":
            store = self.engine.kv_store
            frames = msg.get("_frames") or ()
            if not frames:
                raise ValueError("kv_put without a payload frame")
            if store is None:
                # Fleet-config state, not a protocol error: a worker
                # without a local store just recomputes what the push
                # would have saved.
                return {"stored": False, "load": self._load()}
            # A pushed block is not "new" to the fleet — the front-end
            # already knows it; announce=False keeps it out of the
            # catalog feed without dropping the engine's OWN pending
            # announcements.
            stored = store.put(bytes.fromhex(msg["digest"]),
                               decode_kv_block(frames[0]),
                               announce=False)
            return {"stored": bool(stored), "load": self._load()}
        if method == "kv_get":
            store = self.engine.kv_store
            hit = (None if store is None
                   else store.get(bytes.fromhex(msg["digest"])))
            if hit is None:
                return {"found": False, "load": self._load()}
            tier, leaves = hit
            return {"found": True, "tier": tier,
                    "_frames": [encode_kv_block(leaves)],
                    "load": self._load()}
        if method == "kv_has":
            store = self.engine.kv_store
            digs = [bytes.fromhex(h) for h in msg.get("digests", ())]
            return {"has": [bool(store is not None and store.has(d))
                            for d in digs],
                    "load": self._load()}
        if method == "set_role":
            self.engine.set_role(msg.get("role"))
            return {"load": self._load()}
        if method == "extract":
            self._now_value = float(msg.get("now", self._now_value))
            rid = int(msg["rid"])
            out = self.engine.extract_request(rid)
            if out is None:
                return {"found": False, "load": self._load()}
            req, payload = out
            self._reqs.pop(rid, None)
            self._sent.pop(rid, None)
            result = {"found": True, "req": request_to_wire(req),
                      "tail_ntok": 0, "load": self._load()}
            if payload is not None:
                result["tail_ntok"] = int(payload["tail_ntok"])
                # Block-aligned contexts have no raw tail to ship.
                if payload.get("leaves") is not None:
                    result["_frames"] = [encode_kv_block(payload["leaves"])]
            return result
        if method == "summary":
            return {"summary": _jsonable(self.engine.summary()),
                    "load": self._load()}
        if method == "metrics":
            # Registry snapshot for the front-end merge: callbacks are
            # resolved to plain values here, so the wire carries only
            # JSON scalars (see obs.metrics.MetricsRegistry.snapshot).
            return {"metrics": self.engine.registry.snapshot(),
                    "load": self._load()}
        if method == "reset":
            # Fresh engine, warm process: the jitted step is memoised per
            # frozen config inside this process, so no recompile.
            self._reqs.clear()
            self._sent.clear()
            self.engine = self._build_engine()
            self._steps = 0
            return {"load": self._load()}
        if method == "shutdown":
            self._shutdown = True
            return {}
        raise ValueError(f"unknown method {method!r}")

    # -- the socket loop ---------------------------------------------------

    def serve(self, srv: socket.socket) -> None:
        srv.setblocking(False)
        self._beat()
        while not self._shutdown:
            r, _, _ = select.select([srv], [], [], 0.5)
            self._beat()
            if not r:
                continue
            try:
                conn, _ = srv.accept()
            except OSError:
                continue
            self._serve_conn(conn)
        if self._hb is not None:
            self._hb.stop()

    def _serve_conn(self, conn: socket.socket) -> None:
        conn.setblocking(True)
        try:
            while not self._shutdown:
                r, _, _ = select.select([conn], [], [], 0.5)
                self._beat()
                if not r:
                    continue
                try:
                    msg = recv_frame(conn)
                except FrameError:
                    return              # poisoned stream: drop this client
                if msg is None:
                    return              # clean disconnect
                nf = int(msg.get("nframes", 0) or 0)
                if nf:
                    # Attached binary frames (kv_put payloads, migration
                    # tails) follow the JSON frame immediately. A torn
                    # or over-announced batch poisons this connection
                    # only, exactly like a torn JSON frame.
                    if nf < 0 or nf > MAX_ATTACHED_FRAMES:
                        return
                    try:
                        msg["_frames"] = [
                            recv_binary_frame(conn) for _ in range(nf)]
                    except FrameError:
                        return
                out_frames: List[bytes] = []
                try:
                    result = self.handle(msg)
                    # Binary payloads leave the JSON result and trail the
                    # response as announced attached frames.
                    out_frames = result.pop("_frames", None) or []
                    # Piggyback the engine tracer's span-event delta on
                    # every reply: worker-side events (admitted, prefill
                    # chunks, first_token, spec windows, terminals)
                    # reach the front-end timeline with zero extra
                    # round-trips. Empty when tracing is off.
                    trace = self.engine.tracer.drain()
                    if trace:
                        result["trace"] = trace
                    resp = {"id": msg.get("id"), "ok": True, "result": result}
                except ValueError as e:
                    resp = {"id": msg.get("id"), "ok": False,
                            "error": {"type": "ValueError", "msg": str(e)}}
                except Exception as e:  # keep serving other requests
                    resp = {"id": msg.get("id"), "ok": False,
                            "error": {"type": type(e).__name__,
                                      "msg": str(e)}}
                if out_frames:
                    resp["nframes"] = len(out_frames)
                try:
                    send_frame(conn, _jsonable(resp))
                    for fr in out_frames:
                        send_binary_frame(conn, fr)
                except (OSError, FrameError):
                    return
                self._beat()
        finally:
            try:
                conn.close()
            except OSError:
                pass


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description="one ServingEngine replica behind a JSON-RPC socket")
    p.add_argument("--spec", required=True,
                   help="JSON file: {config, engine kwargs, params_npz}")
    p.add_argument("--socket", default=None,
                   help="unix socket path to listen on (the default "
                        "transport)")
    p.add_argument("--tcp", default=None, metavar="HOST:PORT",
                   help="listen on TCP instead (port 0 = ephemeral)")
    p.add_argument("--addr-file", default=None,
                   help="with --tcp: write the bound host:port here")
    p.add_argument("--heartbeat-dir", default=None)
    p.add_argument("--worker-id", type=int, default=0)
    args = p.parse_args(argv)
    if not args.socket and not args.tcp:
        p.error("one of --socket or --tcp is required")

    with open(args.spec) as f:
        spec = json.load(f)

    # Bind BEFORE the (slow) engine build so the supervisor's connect
    # succeeds immediately; its first RPC simply waits for accept.
    if args.tcp:
        host, port = args.tcp.rsplit(":", 1)
        srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        srv.bind((host, int(port)))
        if args.addr_file:
            bound = srv.getsockname()
            tmp = f"{args.addr_file}.tmp"
            with open(tmp, "w") as f:
                f.write(f"{bound[0]}:{bound[1]}")
            os.replace(tmp, args.addr_file)
    else:
        if os.path.exists(args.socket):
            os.unlink(args.socket)
        srv = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        srv.bind(args.socket)
    srv.listen(4)

    server = WorkerServer(spec, worker_id=args.worker_id,
                          heartbeat_dir=args.heartbeat_dir)
    try:
        server.serve(srv)
    finally:
        srv.close()
        if args.socket and os.path.exists(args.socket):
            try:
                os.unlink(args.socket)
            except OSError:
                pass
    return 0


if __name__ == "__main__":
    sys.exit(main())
