"""Multi-replica serving front-end: prefix-affinity routing, SLO-aware
admission control, replica failover, and capacity-driven resize.

Everything below ``ServingFrontend`` is the single-engine stack
unchanged: each replica is a full ``ServingEngine`` (its own scheduler,
paged block pool, and prefix index) built from the SAME params/config —
``GPTConfig`` is frozen and the jitted step is memoised per config
(``engine._jitted_engine_step``), so N replicas share one compile cache
and cost no extra retraces. The front-end owns the request tier above:

- **Prefix-affinity routing** (``routing="affinity"``): the routing key
  is the chained blake2b digest of the prompt's leading full blocks —
  literally the same hash the per-engine prefix index uses
  (``paged_cache.chained_block_digests``) — mapped to a replica by
  rendezvous (highest-random-weight) hashing over the live set, so the
  mapping is stable under grow/shrink/failover: resizing moves only the
  keys that must move. Shared-prefix traffic therefore lands on the one
  replica whose copy-on-write cache already holds the prefix, instead
  of every replica paying the cold prefill (what ``random`` and pure
  ``least_loaded`` routing cost on correlated traffic). Prompts with no
  full block route least-outstanding-tokens (cold fallback), and a
  ``spill_tokens`` gap threshold sheds an over-affine hot shard to the
  least-loaded survivor so affinity can never starve the rest of the
  fleet.
- **SLO-aware admission control**: per-replica queues are bounded
  (``max_queue_depth``) and carry an oldest-wait age watermark
  (``wait_watermark``, in front-end clock units). A submit that lands
  on a replica past either limit first tries to shed to a live replica
  with room; if none exists the request is REJECTED at submit with a
  structured ``SubmitResult`` (reason, observed depth and wait age) —
  backpressure the caller can act on, never a silently unbounded queue.
  Rejects, queue depths, and wait-age percentiles surface in
  ``summary()``.
- **Replica failover**: ``kill_replica`` (driven by the
  ``replica_kill@N`` fault kind, ``utils/faults.py``) marks a replica
  dead, exports its queued AND in-flight requests with runtime state
  reset (``Scheduler.export_requests``), and resubmits them to the
  survivors. Resumed streams are token-identical to an undisturbed run
  by the preemption-resume argument: re-admission re-prefills prompt +
  generated-so-far and sampling is keyed by (seed, token index), so the
  continuation cannot depend on where — or how often — it was
  interrupted.
- **Capacity-driven resize**: the front-end probes the
  ``utils/preemption.py`` capacity file every ``capacity_probe_every``
  iterations and consumes grants to grow toward ``max_replicas`` (the
  same grant/consume protocol the elastic trainer uses for host
  grow-back). ``shrink`` marks the highest-id replicas draining:
  their waiting requests re-route immediately, their running requests
  finish in place, and the replica is torn down only once idle.

Time: the front-end owns one clock domain shared by every replica
(engines are built with ``clock=`` the front-end's ``_now`` and a zero
epoch), so arrival times, wait ages, and token timestamps are all
comparable across replicas — in seconds (``time_mode="wall"``) or
front-end iterations (``"steps"``, fully deterministic for tests).

Replicas are pluggable (``replica_factory``): the default builds
in-process engines wrapped in ``LocalReplica``; passing a
``serving.remote.WorkerSupervisor`` instead puts each replica in its
own OS process behind the ``serving/worker.py`` RPC loop — same
routing/admission/failover logic, and the same clock domain (every step
RPC ships the front-end's ``now``, so ``steps`` mode stays
deterministic fleet-wide). Worker deaths (SIGKILL exit codes or
heartbeat flatlines, the ``worker_kill`` fault) are polled each step
and drive the same ``kill_replica`` failover as ``replica_kill`` —
dead-worker state is reconstructed from the front-end-side request
mirrors, so queued AND in-flight requests resume bit-identically on
the survivors.

Request lifecycle: beyond finishing, an accepted request can be
**cancelled** (``cancel(rid)`` — effective on waiting AND running
requests, freeing its paged KV blocks immediately on in-process and
RPC replicas alike via the ``cancel`` RPC verb) or can miss its
**deadline** (``Request.deadline``, front-end clock domain; expiry is
swept at each engine iteration boundary). Both are terminal states
counted separately from ``finished``; conservation becomes ``accepted
== finished + cancelled + deadline_exceeded`` at drain. Hung — not
dead — workers (the ``worker_hang`` SIGSTOP fault, or a real wedge)
are caught by per-call RPC timeouts: the blocked call raises
``ReplicaDied``, the supervisor FENCES the suspect (SIGKILL, so a
paused process can never wake up and keep serving a replica the
front-end already failed over), and recovery reuses the exact
``kill_replica`` export/resubmit path — so resumed streams stay
bit-identical and the front-end stall is bounded by the configured
RPC timeout. One-shot transport faults (``net_delay`` / ``net_drop``
/ ``net_garble`` / ``net_hang``) arm the same machinery for chaos
drills.
"""

from __future__ import annotations

import dataclasses
import hashlib
import os
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from tpu_trainer.models.config import GPTConfig
from tpu_trainer.obs.metrics import NULL_REGISTRY, MetricsRegistry
from tpu_trainer.serving.engine import ServingEngine
from tpu_trainer.serving.kv_store import KVBlockStore, leaves_nbytes
from tpu_trainer.serving.paged_cache import chained_block_digests
from tpu_trainer.serving.remote import ReplicaDied
from tpu_trainer.serving.scheduler import Request
from tpu_trainer.serving.tracing import ServingLedger, SpanTracer
from tpu_trainer.utils import faults
from tpu_trainer.utils.flight_recorder import FlightRecorder
from tpu_trainer.utils.logging import SCHEMA_VERSION
from tpu_trainer.utils.preemption import consume_capacity, read_capacity

ROUTINGS = ("affinity", "random", "least_loaded")


@dataclasses.dataclass
class SubmitResult:
    """Structured outcome of one ``submit``: where the request went, or
    why it was shed. ``routed`` records the decision path (affinity /
    cold / spill / random / least_loaded / failover); on a reject it is
    None and ``reason`` says which limit tripped (queue_full |
    wait_watermark), with the depth and wait age observed at the
    decision — the caller's backpressure signal."""

    accepted: bool
    replica: Optional[int] = None
    routed: Optional[str] = None
    reason: Optional[str] = None
    queue_depth: int = 0
    oldest_wait: float = 0.0


class LocalReplica:
    """In-process replica adapter: the narrow engine surface the
    front-end actually consumes, shared verbatim with
    ``serving.remote.RemoteReplica`` so a worker process is a drop-in.
    Anything the front-end wants from a replica goes through here —
    submit, step, load counters, export, release — never through
    engine internals directly."""

    def __init__(self, engine: ServingEngine):
        self.engine = engine

    def submit(self, req: Request, trace: Optional[List[dict]] = None,
               migration: Optional[dict] = None) -> None:
        if trace:
            # Same contract as RemoteReplica: front-door span context
            # merges into the engine's tracer (non-pending — never
            # echoed back to the front-end that already holds it).
            self.engine.tracer.ingest(trace)
        if migration is not None:
            req._kv_migration = migration
        self.engine.scheduler.add(req)

    def step(self) -> List[Request]:
        return self.engine.step()

    def cancel(self, rid: int) -> bool:
        return self.engine.cancel(rid)

    def has_work(self) -> bool:
        return self.engine.scheduler.has_work()

    @property
    def queue_depth(self) -> int:
        return self.engine.queue_depth

    @property
    def outstanding_tokens(self) -> int:
        return self.engine.outstanding_tokens

    def oldest_wait_age(self, now: float) -> float:
        return self.engine.oldest_wait_age(now)

    def export_requests(self, *, waiting_only: bool = False) -> List[Request]:
        return self.engine.export_requests(waiting_only=waiting_only)

    def drain_span_events(self) -> List[dict]:
        """Span events the engine emitted since the last drain — the
        same delta surface ``RemoteReplica`` fills from step replies, so
        the front-end merges both transports identically."""
        return self.engine.tracer.drain()

    def metrics_snapshot(self) -> dict:
        """The engine registry's resolved snapshot — same surface as
        ``RemoteReplica.metrics_snapshot`` (which pulls it over the
        ``metrics`` RPC verb), so the front-end merges both transports
        identically."""
        return self.engine.registry.snapshot()

    def release(self) -> None:
        self.engine.device_cache = None   # drop the KV pools

    # -- disaggregation surface (mirrors RemoteReplica's) ------------------

    def set_role(self, role: Optional[str]) -> None:
        self.engine.set_role(role)

    def migratable_rids(self) -> List[int]:
        return self.engine.migratable_rids()

    def extract(self, rid: int):
        return self.engine.extract_request(rid)

    @property
    def block_size(self) -> int:
        return self.engine.cache_state.block_size

    @property
    def generated_tokens(self) -> int:
        return int(self.engine.stats["generated_tokens"])

    @property
    def prefix_hit_tokens(self) -> int:
        return self.engine.scheduler.prefix_hit_tokens

    @property
    def prompt_tokens(self) -> int:
        return self.engine.scheduler.prompt_tokens

    @property
    def n_preemptions(self) -> int:
        return self.engine.scheduler.n_preemptions

    @property
    def store_hit_tokens_host(self) -> int:
        return int(self.engine.cache_state.store_hit_tokens_host)

    @property
    def store_hit_tokens_disk(self) -> int:
        return int(self.engine.cache_state.store_hit_tokens_disk)


@dataclasses.dataclass
class _Replica:
    """One replica adapter (local or remote) plus its front-end
    bookkeeping. The attribute keeps the name ``engine`` — it holds the
    adapter, whose surface is a strict subset of the engine's."""

    rid: int
    engine: object                     # LocalReplica | remote.RemoteReplica
    alive: bool = True
    draining: bool = False
    finished: int = 0
    routed: Dict[str, int] = dataclasses.field(default_factory=dict)


class ServingFrontend:
    """N in-process ``ServingEngine`` replicas behind one
    submit/step/drain surface."""

    def __init__(
        self,
        params,
        config: GPTConfig,
        *,
        replicas: int = 2,
        routing: str = "affinity",
        affinity_blocks: int = 1,
        spill_tokens: Optional[int] = 512,
        max_queue_depth: int = 64,
        wait_watermark: Optional[float] = None,
        capacity_file: Optional[str] = None,
        max_replicas: Optional[int] = None,
        capacity_probe_every: int = 8,
        time_mode: str = "wall",
        clock=time.perf_counter,
        seed: int = 0,
        replica_factory=None,
        replica_device_sets=None,
        replica_roles: Optional[Sequence[str]] = None,
        trace: bool = True,
        ts_interval: int = 32,
        incident_dir: Optional[str] = None,
        ring_capacity: int = 256,
        metric_logger=None,
        registry=None,
        metrics_pull_every: int = 16,
        **engine_kwargs,
    ):
        if replicas < 1:
            raise ValueError(f"replicas={replicas}")
        if routing not in ROUTINGS:
            raise ValueError(f"routing={routing!r} (one of {ROUTINGS})")
        if affinity_blocks < 1:
            raise ValueError(f"affinity_blocks={affinity_blocks}")
        if max_queue_depth < 1:
            raise ValueError(f"max_queue_depth={max_queue_depth}")
        if time_mode not in ("wall", "steps"):
            raise ValueError(f"time_mode={time_mode!r}")
        self.params = params
        self.config = config
        self.routing = routing
        self.affinity_blocks = affinity_blocks
        self.spill_tokens = spill_tokens
        self.max_queue_depth = max_queue_depth
        self.wait_watermark = wait_watermark
        self.capacity_file = capacity_file
        self.max_replicas = max_replicas
        self.capacity_probe_every = max(1, capacity_probe_every)
        self.time_mode = time_mode
        self.clock = clock
        # A replica_factory makes the replica tier pluggable: called as
        # (rid, clock) -> replica adapter. None = in-process engines.
        # A factory that also exposes poll_deaths/sigkill (i.e. a
        # remote.WorkerSupervisor) is additionally used as the process
        # supervisor: deaths it reports drive kill_replica failover.
        self._replica_factory = replica_factory
        self._supervisor = (replica_factory
                            if hasattr(replica_factory, "poll_deaths")
                            else None)
        # Replica engines inherit the tracing switch so local emission
        # and front-end merging toggle together (a bare bool, so the
        # RPC worker spec serializes it too).
        engine_kwargs.setdefault("trace", trace)
        self._engine_kwargs = engine_kwargs
        # Disaggregated prefill/decode: replica ``rid`` takes role
        # ``replica_roles[rid % len]``. Prefill replicas run chunked
        # prefill + the first token only; the front-end then migrates
        # the finished KV (digest-addressed full blocks via the store,
        # raw tail) to a rendezvous-routed decode replica. Roles are a
        # performance shape, never a correctness dependency — any
        # request can fall back to plain re-prefill anywhere.
        self.replica_roles = list(replica_roles) if replica_roles else None
        if self.replica_roles:
            for r in self.replica_roles:
                if r not in ("prefill", "decode"):
                    raise ValueError(
                        f"replica_roles entry {r!r} (prefill | decode)")
            if "decode" not in self.replica_roles:
                raise ValueError("replica_roles needs a decode replica")
        self._role: Dict[int, str] = {}
        # Fleet-wide KV block store. In-process fleets share ONE store
        # object (a prefix prefilled on any replica is a store hit on
        # every other); RPC fleets give each worker a local store
        # (kv_store_bytes in engine kwargs) synchronized over the
        # kv_put/kv_get verbs, with a digest->holder catalog fed by
        # load-snapshot deltas.
        self.kv_store: Optional[KVBlockStore] = None
        if self._replica_factory is None and (
                engine_kwargs.get("kv_store_bytes")
                or engine_kwargs.get("kv_store_dir")):
            self.kv_store = KVBlockStore(
                host_bytes=int(engine_kwargs.get("kv_store_bytes")
                               or (64 << 20)),
                disk_dir=engine_kwargs.get("kv_store_dir"))
        self._kv_catalog: Dict[bytes, int] = {}
        # Mesh-aware replica placement: one replica = one mesh. Each
        # entry is a device-id list; replica ``rid`` takes entry
        # ``rid % len`` so a fleet carves the host's devices into
        # disjoint tensor-parallel meshes. None = every replica uses
        # the default devices (engine_kwargs may still set mesh_tensor).
        self._replica_device_sets = (
            [tuple(int(d) for d in ds) for ds in replica_device_sets]
            if replica_device_sets else None)
        # Fleet observability: one merged tracer (front-door events plus
        # replica deltas drained after each step), per-replica flight-
        # recorder rings fed off every event, a serve-loop ledger, and
        # periodic serve_ts samples. All host-side — the jitted path
        # and the sampled tokens cannot see any of it.
        self.tracer = SpanTracer(on_event=self._ring_observe, enabled=trace)
        self.ledger = ServingLedger()
        self.ts_interval = int(ts_interval)
        self.incident_dir = incident_dir
        self.ring_capacity = int(ring_capacity)
        self.metric_logger = metric_logger
        self.serve_ts: List[dict] = []
        self.incidents: List[dict] = []
        self._rings: Dict[int, FlightRecorder] = {}
        self._rs = np.random.RandomState(seed)
        self._replicas: List[_Replica] = []
        self._next_rid = 0
        self._iters = 0
        self._t0: Optional[float] = None
        self.wall_elapsed = 0.0
        self.submit_results: Dict[int, SubmitResult] = {}
        self._wait_samples: List[float] = []
        # Wall-clock seconds the front-end lost to a replica step that
        # ended in ReplicaDied (hung-RPC fence or death mid-call) — the
        # observable stall a caller sees before failover kicks in.
        self._stall_samples: List[float] = []
        # finished_at - deadline per deadline-carrying terminal request
        # (cancels excluded): >0 is a miss, the fleet-level mirror of
        # the per-engine deadline accounting.
        self._deadline_margins: List[float] = []
        self.stats: Dict[str, float] = {
            "submitted": 0, "accepted": 0, "rejected": 0,
            "rejected_queue_full": 0, "rejected_wait_watermark": 0,
            "finished": 0, "cancelled": 0, "deadline_exceeded": 0,
            "failed": 0,
            "failover_events": 0, "failed_over_requests": 0,
            "worker_deaths": 0,
            "grows": 0, "shrinks": 0, "retired_replicas": 0,
            "migrations": 0, "migrated_bytes": 0,
            "migration_pushed_blocks": 0, "store_synced_blocks": 0,
            "imbalance_sum": 0.0, "imbalance_samples": 0,
            "imbalance_max": 0.0,
        }
        # Live metrics plane: front-door counters mirror ``stats`` via
        # set_function (zero hot-path cost, exact agreement with
        # summary()); per-replica engine registries are pulled and
        # merged label-wise (replica=N) every ``metrics_pull_every``
        # iterations — from the MAIN thread only, so the scrape thread
        # never races an RPC socket. Off (registry=None) ⇒ a null
        # registry and no pulls: bit-identical to a run without it.
        self.registry = registry if registry is not None else NULL_REGISTRY
        self._metrics_on = registry is not None
        self.metrics_pull_every = max(1, int(metrics_pull_every))
        self._install_metrics()
        for _ in range(replicas):
            self._spawn_replica()
        self.block_size = self._replicas[0].engine.block_size

    def _install_metrics(self) -> None:
        reg = self.registry
        req = reg.counter("frontend_requests_total",
                          "Front-door request events", labelnames=("event",))
        for ev in ("submitted", "accepted", "rejected", "finished",
                   "cancelled", "deadline_exceeded", "failed"):
            req.labels(event=ev).set_function(
                lambda e=ev: self.stats[e])
        rej = reg.counter("frontend_rejects_total",
                          "Admission rejects by tripped limit",
                          labelnames=("reason",))
        for reason in ("queue_full", "wait_watermark"):
            rej.labels(reason=reason).set_function(
                lambda r=reason: self.stats[f"rejected_{r}"])
        for name, key, help_ in (
                ("frontend_failover_events_total", "failover_events",
                 "Replica failovers"),
                ("frontend_failed_over_requests_total",
                 "failed_over_requests", "Requests moved by failover"),
                ("frontend_worker_deaths_total", "worker_deaths",
                 "Worker process deaths (killed, fenced, or crashed)"),
                ("frontend_grows_total", "grows", "Replicas added"),
                ("frontend_shrinks_total", "shrinks", "Replicas drained"),
                ("frontend_retired_replicas_total", "retired_replicas",
                 "Draining replicas torn down")):
            reg.counter(name, help_).set_function(
                lambda k=key: self.stats[k])
        reg.counter("frontend_fenced_total",
                    "Suspect workers fenced (SIGKILL) after a hung RPC"
                    ).set_function(
                        lambda: getattr(self._supervisor, "n_fenced", 0)
                        if self._supervisor is not None else 0)
        reg.counter("frontend_incidents_total", "Incident records"
                    ).set_function(lambda: len(self.incidents))
        rep = reg.gauge("frontend_replicas", "Replica set by state",
                        labelnames=("state",))
        rep.labels(state="live").set_function(lambda: len(self._live()))
        rep.labels(state="draining").set_function(
            lambda: sum(1 for h in self._replicas
                        if h.alive and h.draining))
        rep.labels(state="dead").set_function(
            lambda: sum(1 for h in self._replicas if not h.alive))
        reg.gauge("frontend_queue_depth", "Fleet queued requests"
                  ).set_function(
                      lambda: sum(h.engine.queue_depth
                                  for h in self._replicas if h.alive))
        reg.gauge("frontend_outstanding_tokens",
                  "Fleet token-steps of work owed").set_function(
                      lambda: sum(h.engine.outstanding_tokens
                                  for h in self._replicas if h.alive))
        reg.gauge("frontend_in_flight", "Accepted, not yet terminal"
                  ).set_function(
                      lambda: self.stats["accepted"]
                      - self.stats["finished"] - self.stats["cancelled"]
                      - self.stats["deadline_exceeded"]
                      - self.stats["failed"])
        # Fleet store + disaggregation mirrors. Named frontend_kv_* (NOT
        # kv_store_* — those are the per-engine families that arrive via
        # pull_metrics with replica labels; re-registering them here
        # label-free would conflict in the merge).
        kvb = reg.gauge("frontend_kv_store_bytes",
                        "Shared fleet KV store bytes by tier",
                        labelnames=("tier",))
        kvb.labels(tier="host").set_function(
            lambda: self.kv_store.host_bytes_used
            if self.kv_store is not None else 0)
        kvb.labels(tier="disk").set_function(
            lambda: self.kv_store.disk_bytes_used
            if self.kv_store is not None else 0)
        kvh = reg.counter("frontend_kv_store_hit_tokens_total",
                          "Fleet prefill tokens skipped via store hits",
                          labelnames=("tier",))
        kvh.labels(tier="host").set_function(
            lambda: sum(getattr(h.engine, "store_hit_tokens_host", 0)
                        for h in self._replicas))
        kvh.labels(tier="disk").set_function(
            lambda: sum(getattr(h.engine, "store_hit_tokens_disk", 0)
                        for h in self._replicas))
        for name, key, help_ in (
                ("frontend_kv_migrations_total", "migrations",
                 "Requests migrated prefill->decode"),
                ("frontend_kv_migrated_bytes_total", "migrated_bytes",
                 "KV bytes moved by migration (blocks + raw tails)"),
                ("frontend_kv_pushed_blocks_total",
                 "migration_pushed_blocks",
                 "Store blocks pushed to decode workers for migration"),
                ("frontend_kv_synced_blocks_total", "store_synced_blocks",
                 "Store blocks pushed at submit to symmetric workers")):
            reg.counter(name, help_).set_function(
                lambda k=key: self.stats[k])

    def ready(self) -> bool:
        """Readiness for /healthz: at least one live replica. Flips
        false once the fleet drains to nothing (every replica released)
        — the state serve_bench asserts after close."""
        return any(h.alive for h in self._replicas)

    def statusz(self) -> dict:
        """The /statusz payload: fleet summary plus per-replica pool
        fragmentation where visible (local replicas read their engine;
        remote ones report through the merged registry instead)."""
        out = {"kind": "serving_frontend", "iter": self._iters}
        out["summary"] = {
            k: v for k, v in self.summary().items() if k != "per_replica"}
        out["replicas"] = [
            {"replica": h.rid, "alive": h.alive, "draining": h.draining,
             "role": self._role.get(h.rid), "finished": h.finished}
            for h in self._replicas]
        for h, rec in zip(self._replicas, out["replicas"]):
            if h.alive and isinstance(h.engine, LocalReplica):
                rec.update(h.engine.engine.cache_state.fragmentation())
        return out

    def pull_metrics(self) -> None:
        """Merge every live replica's registry snapshot into the
        front-end registry (labels gain ``replica=N``). MAIN thread
        only — a pull is an RPC on remote fleets, and RPC frames must
        never interleave with the step loop's. A replica that dies
        mid-pull is settled through the normal failover path."""
        if not self._metrics_on:
            return
        for h in list(self._replicas):
            if not h.alive:
                continue
            snap_fn = getattr(h.engine, "metrics_snapshot", None)
            if snap_fn is None:
                return   # custom replica without the surface: skip all
            try:
                snap = snap_fn()
            except ReplicaDied:
                self.stats["worker_deaths"] += 1
                self.kill_replica(h.rid, reason="rpc_death")
                continue
            self.registry.merge(snap, extra_labels={"replica": h.rid})

    # -- replica set -------------------------------------------------------

    def _spawn_replica(self) -> _Replica:
        # Replicas live in the front-end's clock domain: the factory
        # receives ``self._now`` and every replica's timestamps are
        # front-end times (zero epoch) — in-process via clock injection,
        # cross-process by shipping ``now`` on every step RPC. Wait ages
        # computed against request arrival_time are therefore comparable
        # across the whole fleet, and ``steps`` mode stays deterministic
        # even when the replica is another OS process.
        rid = self._next_rid
        if self._replica_factory is not None:
            rep = self._replica_factory(rid, self._now)
        else:
            kw = dict(self._engine_kwargs)
            if self._replica_device_sets:
                dsets = self._replica_device_sets
                kw["mesh_devices"] = dsets[rid % len(dsets)]
            if self.kv_store is not None:
                # Every in-process engine shares the front-end's one
                # store object (kv_store wins over kv_store_bytes/_dir
                # inside the engine) — "cached anywhere" IS the tier.
                kw["kv_store"] = self.kv_store
            if self._metrics_on:
                # Per-engine registry, merged into ours label-wise on
                # each pull — the same shape as a worker process's.
                kw.setdefault("registry", MetricsRegistry())
            eng = ServingEngine(self.params, self.config, clock=self._now,
                                **kw)
            eng._t0 = 0.0
            rep = LocalReplica(eng)
        h = _Replica(rid=rid, engine=rep)
        self._next_rid += 1
        self._replicas.append(h)
        if self.replica_roles:
            role = self.replica_roles[rid % len(self.replica_roles)]
            self._role[rid] = role
            set_role = getattr(rep, "set_role", None)
            if set_role is not None:
                set_role(role)
            elif role == "prefill":
                raise ValueError(
                    "replica adapter has no set_role surface for a "
                    "prefill-role replica")
        return h

    def _live(self, *, routable: bool = False) -> List[_Replica]:
        return [h for h in self._replicas
                if h.alive and not (routable and h.draining)]

    def has_work(self) -> bool:
        return any(h.engine.has_work() for h in self._live())

    def _now(self) -> float:
        if self.time_mode == "steps":
            return float(self._iters)
        if self._t0 is None:
            self._t0 = self.clock()
        return self.clock() - self._t0

    # -- observability -----------------------------------------------------

    def _emit(self, rid, event: str, **attrs) -> None:
        self.tracer.emit(rid, event, self._now(), **attrs)

    def _ring_observe(self, ev: dict) -> None:
        """Every merged span event lands in its replica's ring (capacity
        ``ring_capacity``, oldest evicted) — the raw material an
        incident dump freezes. Front-door events (submit/route, no
        replica yet) share the fleet ring keyed -1."""
        key = int(ev.get("replica", -1))
        ring = self._rings.get(key)
        if ring is None:
            ring = self._rings[key] = FlightRecorder(
                capacity=self.ring_capacity,
                snapshot=self._incident_snapshot)
        ring.observe(ev)

    def _drain_spans(self, h: _Replica) -> None:
        """Merge the replica's span-event delta into the fleet timeline,
        stamped with the replica id. Worker clocks already run in the
        front-end domain (worker.py pins ``_t0 = 0``), so timestamps
        merge without skew correction."""
        if not self.tracer.enabled:
            return
        drain = getattr(h.engine, "drain_span_events", None)
        if drain is None:
            return
        evs = drain()
        for ev in evs:
            ev.setdefault("replica", h.rid)
        self.tracer.ingest(evs)

    def _incident_snapshot(self) -> dict:
        return {
            "iter": self._iters,
            "t": self._now(),
            "replicas_live": len(self._live()),
            "replicas_total": len(self._replicas),
            "queue_depth": sum(
                h.engine.queue_depth for h in self._live()),
            "stats": {k: v for k, v in self.stats.items()
                      if not k.startswith("imbalance_")},
        }

    def _dump_incident(self, reason: str, rid: int) -> Optional[str]:
        """Freeze the span-event ring of the replica an incident hit
        (plus the front-door ring for fleet-level incidents, rid=-1)
        into an atomic ``crash_report.json`` under ``incident_dir``, and
        count a ``kind:"incident"`` record either way. Returns the dump
        directory, or None when ``incident_dir`` is unset."""
        rec = {
            "kind": "incident", "schema_version": SCHEMA_VERSION,
            "reason": reason, "replica": rid,
            "t": round(self._now(), 6), "iter": self._iters,
        }
        self.incidents.append(rec)
        if self.metric_logger is not None:
            self.metric_logger.log_record(rec)
        if not self.incident_dir:
            return None
        ring = self._rings.get(rid)
        if ring is None:
            ring = self._rings[rid] = FlightRecorder(
                capacity=self.ring_capacity,
                snapshot=self._incident_snapshot)
        out = os.path.join(
            self.incident_dir, f"i{self._iters:06d}_{reason}_r{rid}")
        ring.dump(out, reason=reason, step=self._iters)
        rec["dump_dir"] = out
        return out

    def _emit_ts(self, final: bool = False) -> None:
        """One fleet ``serve_ts`` sample: ledger fractions plus cheap
        as-of-now gauges (queue/load gauges read front-end-side request
        mirrors, so no extra RPC round-trips on remote fleets)."""
        live = self._live()
        gauges = {
            "t": round(self._now(), 6),
            "iter": self._iters,
            "replicas_live": len(live),
            "queue_depth": sum(h.engine.queue_depth for h in live),
            "outstanding_tokens": sum(
                h.engine.outstanding_tokens for h in live),
            "in_flight": int(
                self.stats["accepted"] - self.stats["finished"]
                - self.stats["cancelled"]
                - self.stats["deadline_exceeded"] - self.stats["failed"]),
            "finished": int(self.stats["finished"]),
            "rejected": int(self.stats["rejected"]),
            "worker_deaths": int(self.stats["worker_deaths"]),
        }
        rec = self.ledger.record(gauges, final=final)
        self.serve_ts.append(rec)
        if self.metric_logger is not None:
            self.metric_logger.log_record(rec)

    # -- routing -----------------------------------------------------------

    def _prompt_digests(self, req: Request) -> List[bytes]:
        """The request's chained block digests, hashed ONCE at first use
        and cached on the request — the router key, replica admission
        (``Scheduler._admit``), store addressing, and migration all read
        this one list (cross-process too: it rides the request wire
        codec)."""
        if req._prompt_digests is None:
            req._prompt_digests = chained_block_digests(
                req.prompt, self.block_size)
        return req._prompt_digests

    def _affinity_key(self, req) -> Optional[bytes]:
        """Chained digest of the prompt's leading full blocks (capped at
        ``affinity_blocks`` — coarse on purpose: requests sharing a
        system prefix but diverging later must still share a key), or
        None when the prompt has no full block (cold). Accepts a
        ``Request`` (digests cached on the request, hashed once) or a
        raw token sequence for out-of-band probes."""
        if isinstance(req, Request):
            digs = self._prompt_digests(req)
        else:
            digs = chained_block_digests(req, self.block_size)
        n = min(len(digs), self.affinity_blocks)
        if n == 0:
            return None
        return digs[n - 1]

    @staticmethod
    def _rendezvous(key: bytes, cands: List[_Replica]) -> _Replica:
        """Highest-random-weight hashing: each replica scores
        blake2b(key + rid); the max wins. Adding/removing a replica
        remaps only the keys whose winner changed — affinity survives
        resize and failover with minimal cache churn."""
        best, best_score = cands[0], -1
        for h in cands:
            score = int.from_bytes(
                hashlib.blake2b(
                    key + h.rid.to_bytes(8, "little"), digest_size=8
                ).digest(), "little")
            if score > best_score:
                best, best_score = h, score
        return best

    @staticmethod
    def _load(h: _Replica) -> Tuple[int, int]:
        return (h.engine.outstanding_tokens, h.rid)

    def _route(self, req: Request) -> Tuple[_Replica, str]:
        live = self._live(routable=True)
        if not live:
            raise RuntimeError("no live replicas to route to")
        if self.replica_roles:
            # Disaggregated fleets admit at the prefill tier; when no
            # prefill replica survives, the decode fleet admits directly
            # and simply recomputes (roles never gate correctness).
            pre = [h for h in live
                   if self._role.get(h.rid) == "prefill"]
            if pre:
                live = pre
        if self.routing == "random":
            return live[int(self._rs.randint(len(live)))], "random"
        if self.routing == "least_loaded":
            return min(live, key=self._load), "least_loaded"
        key = self._affinity_key(req)
        if key is None:
            return min(live, key=self._load), "cold"
        target = self._rendezvous(key, live)
        least = min(live, key=self._load)
        if (self.spill_tokens is not None
                and target.engine.outstanding_tokens
                - least.engine.outstanding_tokens > self.spill_tokens):
            return least, "spill"
        return target, "affinity"

    def _route_decode(self, req: Request) -> Optional[_Replica]:
        """Pick the decode replica a migrated request lands on:
        rendezvous over the decode tier on the same affinity key (so
        shared-prefix streams co-locate and re-share store fills), cold
        prompts go least-loaded. None when no decode replica is live."""
        live = [h for h in self._live(routable=True)
                if self._role.get(h.rid) != "prefill"]
        if not live:
            return None
        key = self._affinity_key(req)
        if key is None:
            return min(live, key=self._load)
        return self._rendezvous(key, live)

    # -- admission ---------------------------------------------------------

    def _admission_reason(self, h: _Replica, now: float) -> Optional[str]:
        if h.engine.queue_depth >= self.max_queue_depth:
            return "queue_full"
        if (self.wait_watermark is not None
                and h.engine.oldest_wait_age(now) > self.wait_watermark):
            return "wait_watermark"
        return None

    def submit(self, req: Request) -> SubmitResult:
        """Route + admission-check one request. Accepted requests join
        the target replica's waiting queue; past-limit submits first
        shed to a live replica with room and otherwise come back as a
        structured reject — the queue is never unbounded."""
        self.stats["submitted"] += 1
        now = self._now()
        self._emit(req.rid, "submitted")
        target, routed = self._route(req)
        reason = self._admission_reason(target, now)
        if reason is not None:
            alts = [h for h in self._live(routable=True) if h is not target
                    and self._admission_reason(h, now) is None]
            if alts:
                target, routed, reason = min(alts, key=self._load), "spill", None
        if reason is not None:
            self.stats["rejected"] += 1
            self.stats[f"rejected_{reason}"] += 1
            self._emit(req.rid, "rejected", reason=reason)
            res = SubmitResult(
                accepted=False, reason=reason,
                queue_depth=target.engine.queue_depth,
                oldest_wait=target.engine.oldest_wait_age(now))
            self.submit_results[req.rid] = res
            return res
        self._sync_store_to(target, req)
        self._enqueue(target, req, routed)
        res = SubmitResult(
            accepted=True, replica=target.rid, routed=routed,
            queue_depth=target.engine.queue_depth,
            oldest_wait=target.engine.oldest_wait_age(now))
        self.submit_results[req.rid] = res
        return res

    def _enqueue(self, h: _Replica, req: Request, routed: str,
                 migration: Optional[dict] = None) -> None:
        self._emit(req.rid, "routed", replica=h.rid, policy=routed)
        ctx = self.tracer.events(req.rid) if self.tracer.enabled else None
        if migration is not None:
            h.engine.submit(req, trace=ctx, migration=migration)
        else:
            h.engine.submit(req, trace=ctx)
        h.routed[routed] = h.routed.get(routed, 0) + 1
        key = f"routed_{routed}"
        self.stats[key] = self.stats.get(key, 0) + 1
        # failover moves an accepted request; migrate re-admits one —
        # neither is a NEW acceptance.
        if routed not in ("failover", "migrate"):
            self.stats["accepted"] += 1

    def _sync_store_to(self, target: _Replica, req: Request) -> None:
        """Symmetric RPC fleets only: before a remote replica admits,
        push any leading prompt blocks the fleet has computed (per the
        kv_new catalog) but the target's local store lacks. In-process
        fleets get this for free from the one shared store object;
        disaggregated fleets share through the migration path instead.
        Opportunistic — a push failure just means recompute."""
        if self.replica_roles or not self._kv_catalog:
            return
        if not hasattr(target.engine, "kv_put"):
            return
        digs = [d for d in self._prompt_digests(req)
                if self._kv_catalog.get(d) not in (None, target.rid)]
        if not digs:
            return
        try:
            have = target.engine.kv_has(digs)
            for dig, got in zip(digs, have):
                if got:
                    continue
                holder = next(
                    (hh for hh in self._replicas
                     if hh.alive and hh.rid == self._kv_catalog[dig]
                     and hasattr(hh.engine, "kv_get")), None)
                if holder is None:
                    continue
                hit = holder.engine.kv_get(dig)
                if hit is not None and target.engine.kv_put(dig, hit[1]):
                    self._kv_catalog[dig] = target.rid
                    self.stats["store_synced_blocks"] += 1
        except (ReplicaDied, ValueError):
            # A dead side is settled by the next step/poll cycle; a
            # ValueError means the target can't take the push (torn
            # frame, mixed fleet). Either way the request is unaffected
            # (recompute is always correct).
            pass

    # -- cancellation ------------------------------------------------------

    def cancel(self, rid: int) -> bool:
        """Cancel an accepted request wherever it currently lives. The
        request may have moved since submit (failover, shrink), so every
        live replica is asked; the one holding it retires it on the spot
        and frees its paged KV blocks — mid-prefill, mid-decode, or
        mid-speculation. Returns False for unknown, rejected, or
        already-terminal rids. A replica that dies during the cancel RPC
        is failed over (its requests move to survivors) and the scan
        restarts so the moved request is still found."""
        res = self.submit_results.get(rid)
        if res is None or not res.accepted:
            return False
        for _attempt in range(2):
            retry = False
            for h in list(self._replicas):
                if not h.alive:
                    continue
                try:
                    ok = h.engine.cancel(rid)
                except ReplicaDied:
                    self.stats["worker_deaths"] += 1
                    self.kill_replica(h.rid, reason="rpc_death")
                    retry = True
                    break
                if ok:
                    self.stats["cancelled"] += 1
                    self._drain_spans(h)
                    return True
            if not retry:
                break
        return False

    # -- failover ----------------------------------------------------------

    def kill_replica(self, rid: Optional[int] = None, *,
                     reason: str = "replica_kill") -> int:
        """Mark a replica dead and fail its queued + in-flight requests
        over to the survivors (admission limits do not apply — these
        requests were already accepted; shedding them now would break
        the submit-time contract). Default victim: the env override
        ``TPU_TRAINER_FAULT_REPLICA``, else the highest-id live replica
        (mirroring ``faults.target_host``'s highest-rank convention).
        ``reason`` tags the incident record/dump (replica_kill |
        worker_death | rpc_death). Returns the number of requests
        failed over."""
        live = self._live()
        if rid is None:
            raw = os.environ.get("TPU_TRAINER_FAULT_REPLICA")
            rid = int(raw) if raw is not None else max(h.rid for h in live)
        victims = [h for h in live if h.rid == rid]
        if not victims:
            raise ValueError(f"replica {rid} is not alive")
        if len(live) == 1:
            raise RuntimeError("cannot kill the last live replica")
        h = victims[0]
        orphans = h.engine.export_requests()
        self._drain_spans(h)   # capture export/terminal events pre-release
        h.alive = False
        h.engine.release()
        self.stats["failover_events"] += 1
        self.stats["failed_over_requests"] += len(orphans)
        self._dump_incident(reason, h.rid)
        for req in orphans:
            self._emit(req.rid, "failed_over", src=h.rid, reason=reason)
            target, _ = self._route(req)
            self._enqueue(target, req, "failover")
        return len(orphans)

    # -- resize ------------------------------------------------------------

    def grow(self, n: int = 1) -> int:
        """Add up to ``n`` replicas (bounded by ``max_replicas``).
        Returns how many were actually added."""
        added = 0
        while added < n and (self.max_replicas is None
                             or len(self._live()) < self.max_replicas):
            self._spawn_replica()
            added += 1
        self.stats["grows"] += added
        return added

    def shrink(self, n: int = 1) -> int:
        """Mark the ``n`` highest-id live replicas draining: excluded
        from routing immediately, waiting requests re-routed now,
        running requests finish in place; teardown happens in ``step``
        once the replica is idle. Never drains the last live replica."""
        done = 0
        while done < n and len(self._live(routable=True)) > 1:
            h = max(self._live(routable=True), key=lambda x: x.rid)
            h.draining = True
            orphans = h.engine.export_requests(waiting_only=True)
            self._drain_spans(h)
            for req in orphans:
                self._emit(req.rid, "failed_over", src=h.rid, reason="shrink")
                target, _ = self._route(req)
                self._enqueue(target, req, "failover")
            done += 1
        self.stats["shrinks"] += done
        return done

    def _probe_capacity(self) -> int:
        """Consume pending capacity grants into new replicas (the PR 9
        grant/consume protocol: a single agent grants, we consume)."""
        if not self.capacity_file:
            return 0
        room = ((self.max_replicas - len(self._live()))
                if self.max_replicas is not None else None)
        grant = read_capacity(self.capacity_file)
        take = grant if room is None else min(grant, max(0, room))
        if take <= 0:
            return 0
        consume_capacity(self.capacity_file, take)
        return self.grow(take)

    def _reap_draining(self) -> None:
        for h in self._replicas:
            if h.alive and h.draining and not h.engine.has_work():
                self._drain_spans(h)
                h.alive = False
                h.engine.release()
                self.stats["retired_replicas"] += 1

    # -- the per-iteration surface ----------------------------------------

    def step(self) -> List[Request]:
        """One front-end iteration: fire armed ``replica_kill`` /
        ``worker_kill`` / ``worker_hang`` / ``net_*`` faults, settle
        worker-process deaths into failover, probe the capacity file,
        reap drained replicas, then advance every live replica with
        work by one engine step. Returns the requests finished this
        iteration (all replicas); other terminal outcomes (cancelled,
        deadline_exceeded, failed) are counted into ``stats``."""
        self._iters += 1
        if faults.fire("replica_kill", self._iters):
            self.kill_replica()
        if faults.fire("worker_kill", self._iters):
            # A REAL kill: SIGKILL the worker process; the death is
            # settled and failed over through poll_deaths just below —
            # the exact path an unplanned worker death takes.
            if self._supervisor is None:
                raise RuntimeError(
                    "worker_kill fault armed but replicas are in-process")
            self._supervisor.sigkill()
        if faults.fire("worker_hang", self._iters):
            # A hang, not a death: SIGSTOP freezes the worker mid-
            # service. Nothing exits, so poll_deaths sees no exit code;
            # the next step RPC blocks until the per-call timeout, the
            # supervisor fences (SIGKILLs) the suspect, and the same
            # kill_replica failover resumes its streams — the stall is
            # bounded by the configured RPC timeout.
            if self._supervisor is None:
                raise RuntimeError(
                    "worker_hang fault armed but replicas are in-process")
            self._supervisor.sigstop()
        for kind in ("net_delay", "net_drop", "net_garble", "net_hang"):
            if faults.fire(kind, self._iters):
                self._arm_net_fault(kind)
        with self.ledger.track("host_sched"):
            self._settle_worker_deaths()
            if (self.capacity_file
                    and self._iters % self.capacity_probe_every == 0):
                self._probe_capacity()
            self._reap_draining()
        finished: List[Request] = []
        for h in self._replicas:
            if h.alive and h.engine.has_work():
                # An in-process replica step IS the jitted dispatch; a
                # remote one is time blocked on the step RPC reply.
                cat = ("dispatch" if isinstance(h.engine, LocalReplica)
                       else "rpc_wait")
                t_step = time.perf_counter()
                try:
                    with self.ledger.track(cat):
                        out = h.engine.step()
                except ReplicaDied:
                    # Died — or was fenced as hung — mid-RPC: any tokens
                    # the worker generated but never reported are simply
                    # re-generated on the survivor — sampling is keyed
                    # (seed, token_index), so the resumed stream is
                    # unchanged. The elapsed time on the failed call is
                    # the front-end's observable stall.
                    self._stall_samples.append(
                        time.perf_counter() - t_step)
                    self.stats["worker_deaths"] += 1
                    self.kill_replica(h.rid, reason="rpc_death")
                    continue
                self._drain_spans(h)
                for r in out:
                    if r.status == "finished":
                        h.finished += 1
                        finished.append(r)
                    else:
                        self.stats[r.status] += 1
                    self._observe_deadline(r)
        self.stats["finished"] += len(finished)
        with self.ledger.track("host_sched"):
            self._migrate_ready()
            self._catalog_update()
            self._sample_load()
            if (self._metrics_on
                    and self._iters % self.metrics_pull_every == 0):
                self.pull_metrics()
        if self.ts_interval and self._iters % self.ts_interval == 0:
            self._emit_ts()
        return finished

    # -- prefill -> decode migration ---------------------------------------

    def _migrate_ready(self) -> None:
        """Sweep prefill-role replicas for prefill-complete requests and
        move each to the decode tier: full prompt blocks travel digest-
        addressed through the store (shared object in-process, kv_put
        pushes cross-process), the sub-block tail rides the submit as a
        raw binary frame, and the decode replica admits with its cursor
        already past everything transferred. Admission prices every
        block against recompute — a declined transfer is recomputed,
        never wrong."""
        if not self.replica_roles:
            return
        for h in list(self._replicas):
            if not h.alive or self._role.get(h.rid) != "prefill":
                continue
            try:
                self._migrate_from(h)
            except ReplicaDied:
                # The prefill worker died mid-harvest (the chaos lane:
                # SIGKILL mid-migration). Whatever it still held —
                # extracted or not — fails over through the normal
                # export path and re-prefills on the survivors.
                self.stats["worker_deaths"] += 1
                self.kill_replica(h.rid, reason="rpc_death")

    def _migrate_from(self, h: _Replica) -> None:
        for rid in list(h.engine.migratable_rids()):
            out = h.engine.extract(rid)
            if out is None:
                continue
            req, payload = out
            payload = payload or {"tail_ntok": 0, "leaves": None}
            target = self._route_decode(req)
            if target is None:
                # No decode replica left: demote this prefill replica
                # and finish the stream in place — roles are a
                # performance shape, never a correctness dependency.
                self._demote(h)
                self._enqueue(h, req, "migrate", migration=payload)
                continue
            digs = self._prompt_digests(req)
            nbytes = (leaves_nbytes(payload["leaves"])
                      if payload.get("leaves") is not None else 0)
            if self.kv_store is not None:
                for dig in digs:
                    nbytes += int(self.kv_store.entry_nbytes(dig) or 0)
            try:
                nbytes += self._push_blocks(h, target, digs)
                self._emit(req.rid, "migrated", src=h.rid,
                           dst=target.rid, nbytes=nbytes)
                self._enqueue(target, req, "migrate", migration=payload)
            except ReplicaDied:
                # The DECODE side died mid-push/submit: settle it, then
                # hand the request to whatever is left via the failover
                # path (plain re-prefill — pushes are never load-bearing
                # for correctness).
                self.stats["worker_deaths"] += 1
                self.kill_replica(target.rid, reason="rpc_death")
                alt, _ = self._route(req)
                self._enqueue(alt, req, "failover")
                continue
            self.stats["migrations"] += 1
            self.stats["migrated_bytes"] += nbytes

    def _push_blocks(self, src: _Replica, dst: _Replica,
                     digs: List[bytes]) -> int:
        """Cross-process block transfer for one migration: pull each
        digest the target's store lacks from the source worker and push
        it. Returns bytes pushed. Raises ``ReplicaDied`` only for the
        DESTINATION; a source-side failure just truncates the pulls
        (the target recomputes what never arrived)."""
        if not digs or not hasattr(dst.engine, "kv_put"):
            return 0
        have = dst.engine.kv_has(digs)
        pulled = []
        try:
            for dig, got in zip(digs, have):
                if got:
                    continue
                hit = (src.engine.kv_get(dig)
                       if hasattr(src.engine, "kv_get") else None)
                if hit is not None:
                    pulled.append((dig, hit[1]))
        except ReplicaDied:
            pass
        nbytes = 0
        for dig, leaves in pulled:
            try:
                stored = dst.engine.kv_put(dig, leaves)
            except ValueError:
                # The target can't take pushes (no local store, torn
                # frame): it recomputes instead — pushes are never
                # load-bearing. Only ReplicaDied may escape this loop.
                break
            if not stored:
                continue
            self._kv_catalog[dig] = dst.rid
            nbytes += leaves_nbytes(leaves)
            self.stats["migration_pushed_blocks"] += 1
        return nbytes

    def _demote(self, h: _Replica) -> None:
        self._role[h.rid] = "decode"
        set_role = getattr(h.engine, "set_role", None)
        if set_role is not None:
            set_role(None)

    def _catalog_update(self) -> None:
        """Fold every replica's newly-stored digests (piggybacked on
        load snapshots) into the digest->holder catalog — the submit-
        time sync's map of who can serve a kv_get. The in-process shared
        store needs no catalog; its delta is drained and dropped so the
        list stays bounded."""
        if self.kv_store is not None:
            self.kv_store.drain_new_digests()
            return
        for h in self._replicas:
            if not h.alive:
                continue
            drain = getattr(h.engine, "drain_new_digests", None)
            if drain is None:
                continue
            for dig in drain():
                self._kv_catalog[dig] = h.rid

    def _arm_net_fault(self, kind: str) -> None:
        """Arm a one-shot transport fault on one replica's next RPC.
        Victim selection mirrors ``kill_replica``: the
        ``TPU_TRAINER_FAULT_REPLICA`` env override, else the highest-id
        live replica. In-process replicas have no transport to fault."""
        live = self._live()
        raw = os.environ.get("TPU_TRAINER_FAULT_REPLICA")
        rid = int(raw) if raw is not None else max(h.rid for h in live)
        victims = [h for h in live if h.rid == rid]
        if not victims:
            raise ValueError(f"replica {rid} is not alive")
        rep = victims[0].engine
        if not hasattr(rep, "inject_net_fault"):
            raise RuntimeError(
                f"{kind} fault armed but replica {rid} is in-process")
        rep.inject_net_fault(kind)

    def _observe_deadline(self, r: Request) -> None:
        if (r.deadline is not None and r.status != "cancelled"
                and r.finished_at is not None):
            self._deadline_margins.append(r.finished_at - r.deadline)

    def _settle_worker_deaths(self) -> None:
        if self._supervisor is None:
            return
        for rid in self._supervisor.poll_deaths():
            if any(h.rid == rid and h.alive for h in self._replicas):
                self.stats["worker_deaths"] += 1
                self.kill_replica(rid, reason="worker_death")

    def _sample_load(self) -> None:
        live = self._live()
        outs = [h.engine.outstanding_tokens for h in live]
        total = sum(outs)
        if outs and total > 0:
            imb = max(outs) / (total / len(outs))
            self.stats["imbalance_sum"] += imb
            self.stats["imbalance_samples"] += 1
            self.stats["imbalance_max"] = max(self.stats["imbalance_max"], imb)
        now = self._now()
        self._wait_samples.append(
            max((h.engine.oldest_wait_age(now) for h in live), default=0.0))

    def drain(self, max_iters: int = 10_000_000) -> List[Request]:
        """Step until every replica is idle; returns everything finished
        along the way."""
        finished: List[Request] = []
        while self.has_work():
            finished.extend(self.step())
            if self._iters >= max_iters:
                raise RuntimeError(
                    f"front-end did not drain in {max_iters} iters")
        self._reap_draining()
        self.pull_metrics()
        return finished

    # -- trace replay ------------------------------------------------------

    def run(self, requests: Sequence[Request], *,
            max_iters: int = 10_000_000) -> List[Request]:
        """Replay an open-loop trace (same contract as ``ServingEngine.
        run``): each request is SUBMITTED — routing + admission — when
        the clock passes its ``arrival_time``; rejected requests simply
        never finish (their ``SubmitResult`` is in ``submit_results``),
        and cancelled / deadline-expired requests are likewise absent
        from the return — their terminal state lives on the request
        object and in ``stats``. Returns the finished requests in
        input order."""
        pending = sorted(requests, key=lambda r: (r.arrival_time, r.rid))
        t_start = self.clock()
        if self.time_mode == "wall" and self._t0 is None:
            self._t0 = t_start
        done: List[Request] = []
        while pending or self.has_work():
            now = self._now()
            with self.ledger.track("host_sched"):
                while pending and pending[0].arrival_time <= now:
                    self.submit(pending.pop(0))
            if not self.has_work():
                if not pending:
                    break
                with self.ledger.track("idle"):
                    if self.time_mode == "wall":
                        time.sleep(min(
                            1e-3, max(0.0, pending[0].arrival_time - now)))
                    else:
                        self._iters += 1   # idle tick: step clock advances
                continue
            done.extend(self.step())
            if self._iters >= max_iters:
                raise RuntimeError(
                    f"front-end did not drain in {max_iters} iters")
        self._reap_draining()
        self.pull_metrics()
        self.wall_elapsed = self.clock() - t_start
        if self.ts_interval:
            self._emit_ts(final=True)
        # Span-conservation sweep: a drained run that still has open
        # timelines dropped a terminal event somewhere — freeze the
        # front-door ring so there is an artifact to debug from.
        if self.tracer.enabled and not self.tracer.conservation()["ok"]:
            self._dump_incident("drain_failure", -1)
        by_rid = {r.rid: r for r in done if r.status == "finished"}
        return [by_rid[r.rid] for r in requests if r.rid in by_rid]

    # -- telemetry ---------------------------------------------------------

    def summary(self) -> Dict[str, object]:
        """Fleet-level accounting. Conservation invariants (tested):
        ``accepted + rejected == submitted`` always, and ``accepted ==
        finished + cancelled + deadline_exceeded`` once drained —
        failover moves a request, it never duplicates or drops one, and
        every accepted request reaches exactly one terminal state."""
        s: Dict[str, object] = {
            k: v for k, v in self.stats.items()
            if not k.startswith("imbalance_")}
        live = self._live()
        s["replicas_live"] = len(live)
        s["replicas_total"] = len(self._replicas)
        s["in_flight"] = int(
            self.stats["accepted"] - self.stats["finished"]
            - self.stats["cancelled"] - self.stats["deadline_exceeded"]
            - self.stats["failed"])
        s["reject_rate"] = (
            self.stats["rejected"] / max(1, self.stats["submitted"]))
        # Load sums count every NON-DEAD replica, draining included — a
        # draining replica still runs its admitted work, so excluding it
        # would under-report fleet load while the all-replica token
        # counters below still count its tokens (pinned by test).
        loaded = [h for h in self._replicas if h.alive]
        s["queue_depth"] = sum(h.engine.queue_depth for h in loaded)
        s["outstanding_tokens"] = sum(
            h.engine.outstanding_tokens for h in loaded)
        n = max(1, int(self.stats["imbalance_samples"]))
        s["load_imbalance_mean"] = self.stats["imbalance_sum"] / n
        s["load_imbalance_max"] = self.stats["imbalance_max"]
        if self._wait_samples:
            s["wait_age_p50"] = float(np.percentile(self._wait_samples, 50))
            s["wait_age_p99"] = float(np.percentile(self._wait_samples, 99))
        hit = sum(h.engine.prefix_hit_tokens for h in self._replicas)
        prompt = sum(h.engine.prompt_tokens for h in self._replicas)
        gen = sum(h.engine.generated_tokens for h in self._replicas)
        s["prompt_tokens"] = prompt
        s["prefix_hit_tokens"] = hit
        s["prefix_hit_rate"] = hit / max(1, prompt)
        # Token-weighted across every replica, store-tier fills counted
        # (admission folds store hits into prefix_hit_tokens) — THE
        # fleet number the store exists to move: per-replica affinity
        # can only reach its local ceiling; "cached anywhere, hit
        # everywhere" pushes past it.
        s["fleet_prefix_hit_rate"] = hit / max(1, prompt)
        sh_host = sum(getattr(h.engine, "store_hit_tokens_host", 0)
                      for h in self._replicas)
        sh_disk = sum(getattr(h.engine, "store_hit_tokens_disk", 0)
                      for h in self._replicas)
        s["store_hit_tokens_host"] = int(sh_host)
        s["store_hit_tokens_disk"] = int(sh_disk)
        s["store_hit_tokens"] = int(sh_host + sh_disk)
        if self.kv_store is not None:
            for k, v in self.kv_store.stats().items():
                s[f"kv_store_{k}"] = v
        s["generated_tokens"] = gen
        s["iters"] = self._iters
        if self.wall_elapsed:
            s["wall_s"] = self.wall_elapsed
            s["tokens_per_s"] = gen / self.wall_elapsed
        s["per_replica"] = [
            {
                "replica": h.rid,
                "alive": h.alive,
                "draining": h.draining,
                "role": self._role.get(h.rid),
                "finished": h.finished,
                "routed": dict(h.routed),
                "generated_tokens": h.engine.generated_tokens,
                "prefix_hit_rate": (
                    h.engine.prefix_hit_tokens
                    / max(1, h.engine.prompt_tokens)),
                "store_hit_tokens": int(
                    getattr(h.engine, "store_hit_tokens_host", 0)
                    + getattr(h.engine, "store_hit_tokens_disk", 0)),
                "preemptions": h.engine.n_preemptions,
            }
            for h in self._replicas
        ]
        s["transport"] = ("rpc" if self._supervisor is not None
                          or any(not isinstance(h.engine, LocalReplica)
                                 for h in self._replicas)
                          else "inproc")
        s["worker_deaths"] = int(self.stats["worker_deaths"])
        if self.tracer.enabled:
            cons = self.tracer.conservation()
            s["span_events"] = len(self.tracer)
            s["span_conservation_ok"] = bool(cons["ok"])
            s["span_open"] = len(cons["open"])
            s["span_multi_terminal"] = len(cons["multi_terminal"])
        s["incidents"] = len(self.incidents)
        if self._stall_samples:
            s["stall_recovery_max_s"] = float(max(self._stall_samples))
        if self._supervisor is not None:
            s["fenced"] = int(getattr(self._supervisor, "n_fenced", 0))
        if self._deadline_margins:
            margins = np.asarray(self._deadline_margins)
            slack = np.maximum(margins, 0.0)
            s["deadline_miss_rate"] = float(np.mean(margins > 0))
            s["deadline_miss_slack_p50"] = float(np.percentile(slack, 50))
            s["deadline_miss_slack_p99"] = float(np.percentile(slack, 99))
        return s
