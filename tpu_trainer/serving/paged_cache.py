"""Paged KV cache: a refcounted block pool with free-list allocation,
host mirrors, and a copy-on-write prefix index.

The device side is the flax cache collection the paged decode path
creates (``models/gpt.py _paged_decode_attention``): per-layer k/v pools
``[num_blocks, block_size, kvh, head_dim]`` (fp or int8 + scales), block
tables ``[slots, max_blocks]``, lengths ``[slots]`` and chunk offsets
``[slots]``. The pools are the only *persistent* device state — tables,
lengths and offsets are re-broadcast from the host mirrors kept here
before every jitted step, so all scheduling (allocation, reclaim,
preemption, prefix sharing) is plain deterministic Python with zero
device syncs.

Block 0 is reserved as the null block: unallocated table entries point
at it, and the model's scatter redirects masked writes (prefill padding,
idle slots) there. Reads always mask by length, so its garbage is never
observed — this is what lets the scatter and the jitted step run
unpredicated over the full slot batch.

**Prefix caching** (``prefix_cache=True``): full blocks of a prompt are
content-addressed by a chained digest (blake2b over the parent block's
digest + the block's token ids — so a block's identity pins its whole
left context). A new request whose leading full blocks hit the index
shares those physical blocks instead of re-prefilling them; sharing is
copy-on-write *by construction*: the matched length is always rounded
down to a block boundary strictly inside the prompt, so every write a
request ever makes (remaining prefill + decode) lands in blocks it
allocated privately. The index itself holds one reference per entry —
a block is reclaimable only when its refcount reaches zero, and index
entries whose block is otherwise unreferenced form the LRU eviction
pool that backstops allocation.
"""

from __future__ import annotations

import hashlib
from collections import OrderedDict
from typing import List, Optional, Tuple

import numpy as np


def chained_block_digests(tokens: List[int], block_size: int) -> List[bytes]:
    """Chained content digests of ``tokens``' FULL blocks: digest[i] =
    blake2b(digest[i-1] + block i's token bytes), so equal digests imply
    equal token prefixes up to and including block i. Shared by the
    per-engine prefix index (``PagedKVCache.block_digests``) and the
    multi-replica router's prefix-affinity key (``serving/frontend.py``)
    — one hash function, so "the replica this prompt routes to" and "the
    blocks that prompt can share" agree by construction."""
    out: List[bytes] = []
    parent = b""
    for i in range(len(tokens) // block_size):
        blk = np.asarray(
            tokens[i * block_size:(i + 1) * block_size], np.int32)
        parent = hashlib.blake2b(
            parent + blk.tobytes(), digest_size=16).digest()
        out.append(parent)
    return out


class BlockPool:
    """Refcounted free-list allocator over ``num_blocks`` pool blocks
    (id 0 reserved).

    LIFO free list with deterministic order: the same request sequence
    always produces the same block ids — part of the engine's
    deterministic-replay contract. ``alloc`` hands out blocks at
    refcount 1; ``retain`` adds a reference (prefix sharing); ``free``
    drops one and only returns the block to the free list when the
    count hits zero, so a shared block is never reclaimed while any
    request (or the prefix index) still points at it.
    """

    def __init__(self, num_blocks: int):
        if num_blocks < 2:
            raise ValueError("need >= 2 blocks (block 0 is the null block)")
        self.num_blocks = num_blocks
        # pop() hands out ascending ids on a fresh pool.
        self._free: List[int] = list(range(num_blocks - 1, 0, -1))
        self._ref = np.zeros((num_blocks,), np.int32)

    @property
    def free_blocks(self) -> int:
        return len(self._free)

    @property
    def used_blocks(self) -> int:
        return (self.num_blocks - 1) - len(self._free)

    @property
    def occupancy(self) -> float:
        return self.used_blocks / (self.num_blocks - 1)

    def refcount(self, bid: int) -> int:
        return int(self._ref[bid])

    def alloc(self, n: int) -> Optional[List[int]]:
        """Allocate ``n`` blocks at refcount 1, or None (untouched pool)
        if short."""
        if n < 0:
            raise ValueError(f"alloc({n})")
        if n > len(self._free):
            return None
        out = [self._free.pop() for _ in range(n)]
        self._ref[out] = 1
        return out

    def retain(self, ids) -> None:
        """Add one reference to each (already-allocated) block."""
        for bid in ids:
            if not 0 < bid < self.num_blocks:
                raise ValueError(f"retaining invalid block id {bid}")
            if self._ref[bid] == 0:
                raise ValueError(f"retain of free block {bid}")
            self._ref[bid] += 1

    def free(self, ids) -> None:
        """Drop one reference per block; refcount-0 blocks return to the
        free list. Freeing an already-free block raises (double free)."""
        for bid in ids:
            if not 0 < bid < self.num_blocks:
                raise ValueError(f"freeing invalid block id {bid}")
            if self._ref[bid] == 0:
                raise ValueError(f"double free of block {bid}")
            self._ref[bid] -= 1
            if self._ref[bid] == 0:
                self._free.append(bid)


class PagedKVCache:
    """Host mirrors (tables, lengths, offsets, pool, prefix index) for
    one engine's slot batch."""

    def __init__(self, config, slots: int, *, prefix_cache: bool = False,
                 kv_store=None):
        if not config.decode_paged:
            raise ValueError("PagedKVCache needs config.decode_paged=True")
        self.config = config
        self.slots = slots
        self.block_size = config.paged_block_size
        self.max_blocks = config.paged_max_blocks
        self.pool = BlockPool(config.paged_num_blocks)
        self.tables = np.zeros((slots, self.max_blocks), np.int32)
        self.lengths = np.zeros((slots,), np.int32)
        self._n_blocks = np.zeros((slots,), np.int32)  # allocated per slot
        # Prefix index: chained block digest -> block id, in LRU order
        # (oldest first). Each entry holds one pool reference.
        self.prefix_cache = prefix_cache
        self._prefix: "OrderedDict[bytes, int]" = OrderedDict()
        self.n_prefix_evictions = 0
        # Fleet tier behind the device pool (serving/kv_store.py).
        # Device I/O is the owning engine's job — it installs the hooks:
        # ``spill_fn(digest, bid) -> bool`` reads a device block into the
        # store, ``fill_fn(digest, bid) -> tier|None`` writes store bytes
        # into a device block, ``raw_fill_fn(bid, leaves) -> bool`` the
        # same for a migrated raw tail, ``pricer`` the migration-vs-
        # recompute admission gate (kv_store.MigrationPricer).
        self.store = kv_store
        self.spill_fn = None
        self.fill_fn = None
        self.raw_fill_fn = None
        self.pricer = None
        self.n_store_spills = 0
        self.n_store_declined = 0      # store hits priced out of transfer
        self.store_hit_tokens_host = 0
        self.store_hit_tokens_disk = 0

    def blocks_for(self, n_tokens: int) -> int:
        """Blocks needed to hold ``n_tokens``."""
        return -(-n_tokens // self.block_size)

    def capacity_tokens(self) -> int:
        """Per-request token ceiling (the table width)."""
        return self.max_blocks * self.block_size

    def assign(self, slot: int, block_ids: List[int]) -> None:
        """Install an allocation into an empty slot's table row."""
        assert self._n_blocks[slot] == 0, f"slot {slot} not released"
        n = len(block_ids)
        assert n <= self.max_blocks
        self.tables[slot, :n] = block_ids
        self._n_blocks[slot] = n

    def extend(self, slot: int, block_ids: List[int]) -> None:
        n0 = int(self._n_blocks[slot])
        n = len(block_ids)
        assert n0 + n <= self.max_blocks, f"slot {slot} table overflow"
        self.tables[slot, n0:n0 + n] = block_ids
        self._n_blocks[slot] = n0 + n

    def slot_blocks(self, slot: int) -> List[int]:
        return [int(b) for b in self.tables[slot, :self._n_blocks[slot]]]

    def release(self, slot: int) -> None:
        """Drop the slot's references (blocks shared with the prefix
        index or other slots survive) and null its table row."""
        self.pool.free(self.slot_blocks(slot))
        self.tables[slot] = 0
        self.lengths[slot] = 0
        self._n_blocks[slot] = 0

    def shrink(self, slot: int, keep_blocks: int) -> int:
        """Drop the slot's TRAILING blocks past ``keep_blocks`` — the
        speculative-decode rejection rewind (serving/spec.py): blocks
        grown for a draft window whose tokens the verifier rejected go
        back to the pool the same iteration. Refcount-safe by the same
        argument as ``release`` (a block shared with the prefix index
        survives there), though in practice the tail past a request's
        cached tokens is always private: shared blocks are full PROMPT
        blocks at the front of the table. Returns blocks freed."""
        n0 = int(self._n_blocks[slot])
        if keep_blocks >= n0:
            return 0
        assert keep_blocks >= 1, f"shrink(slot={slot}, keep={keep_blocks})"
        tail = [int(b) for b in self.tables[slot, keep_blocks:n0]]
        self.pool.free(tail)
        self.tables[slot, keep_blocks:n0] = 0
        self._n_blocks[slot] = keep_blocks
        return len(tail)

    # -- prefix index ------------------------------------------------------

    def block_digests(self, tokens: List[int]) -> List[bytes]:
        """Chained content digests of ``tokens``' FULL blocks: digest[i]
        = blake2b(digest[i-1] + block i's token bytes), so equal digests
        imply equal token prefixes up to and including block i."""
        return chained_block_digests(tokens, self.block_size)

    def prefix_lookup(self, prompt: List[int], *,
                      digests: Optional[List[bytes]] = None,
                      context_len: Optional[int] = None,
                      ) -> Tuple[List[int], int]:
        """Longest indexed prefix of ``prompt``, as ``(block_ids,
        matched_tokens)``. The match is capped at the last full block
        strictly inside the context (at least one token is always fed —
        its logit seeds/continues generation), which also makes sharing
        copy-on-write by construction: the requester's first write
        starts at a block boundary in a private block. Hits touch the
        LRU order. Returns ``([], 0)`` when the index is off.

        The returned blocks carry ONE caller-owned reference each (on
        top of the index's): the caller either installs them in a slot
        table — ``release``/``shrink`` drop the reference later — or
        must ``pool.free`` them when admission is abandoned. Retaining
        eagerly, inside the walk, is what keeps the match safe: the
        store fall-through allocates a device block per missed digest,
        and that allocation's eviction backstop may only reclaim
        refcount-1 index entries — which an un-retained match still is.

        ``digests`` skips re-hashing when the caller already computed
        the prompt's chained digests (cached on the ``Request`` at
        submit). ``context_len`` widens the cap for requests resuming
        with generated tokens (KV migration): with context past the
        prompt, *every* full prompt block is matchable — the fed token
        is a generated one.

        A device-index miss falls through to the fleet store: a stored
        digest is filled into a freshly allocated device block and
        adopted into the index, so admission skips prefill for any
        block the fleet has ever computed (subject to the migration
        pricer preferring transfer over recompute)."""
        if not self.prefix_cache:
            return [], 0
        ctx = len(prompt) if context_len is None else context_len
        k_max = max(0, min(len(prompt), ctx - 1) // self.block_size)
        if digests is None:
            digests = self.block_digests(prompt[:k_max * self.block_size])
        shared: List[int] = []
        for i in range(min(k_max, len(digests))):
            dig = digests[i]
            bid = self._prefix.get(dig)
            if bid is None:
                bid = self._store_fill(dig)
            if bid is None:
                break
            # Pin the match NOW: a later digest's store fall-through
            # allocates a fill block, and at refcount 1 this match would
            # be fair game for that allocation's eviction backstop — the
            # freed id could even come back as the fill target, leaving
            # ``shared`` pointing at a different digest's K/V.
            self.pool.retain([bid])
            self._prefix.move_to_end(dig)
            shared.append(bid)
        return shared, len(shared) * self.block_size

    def _store_fill(self, dig: bytes) -> Optional[int]:
        """Fleet-store fall-through for one missed digest: allocate a
        device block, fill it from the store, adopt it into the prefix
        index (the alloc reference becomes the index reference, so the
        filled block is refcounted exactly like a locally computed
        entry). None on store miss, pricer veto, or a dry pool."""
        if self.store is None or self.fill_fn is None:
            return None
        if not self.store.has(dig):
            return None
        if self.pricer is not None:
            nbytes = self.store.entry_nbytes(dig) or 0
            if not self.pricer.prefers_transfer(self.block_size, nbytes):
                self.n_store_declined += 1
                return None
        got = self.alloc_blocks(1)
        if got is None:
            return None
        bid = got[0]
        tier = self.fill_fn(dig, bid)
        if tier is None:
            self.pool.free([bid])
            return None
        self._prefix[dig] = bid
        if tier == "disk":
            self.store_hit_tokens_disk += self.block_size
        else:
            self.store_hit_tokens_host += self.block_size
        return bid

    def fill_raw(self, block_id: int, leaves) -> bool:
        """Write a migrated raw (tail) block's leaves into a private
        device block via the engine hook. False when no hook is
        installed or the payload doesn't match the pool layout."""
        if self.raw_fill_fn is None:
            return False
        return bool(self.raw_fill_fn(block_id, leaves))

    def prefix_register(self, digest: bytes, block_id: int) -> bool:
        """Publish a freshly filled full block under its digest. The
        index takes its own reference. No-op (False) when the digest is
        already indexed — concurrent identical prompts that both missed
        keep their private copies — or when the index is off."""
        if not self.prefix_cache or digest in self._prefix:
            return False
        self.pool.retain([block_id])
        self._prefix[digest] = block_id
        return True

    @property
    def evictable_blocks(self) -> int:
        """Index entries whose block is referenced by the index alone."""
        return sum(1 for bid in self._prefix.values()
                   if self.pool.refcount(bid) == 1)

    @property
    def available_blocks(self) -> int:
        """Free blocks plus what LRU eviction could reclaim — the
        admission budget."""
        return self.pool.free_blocks + self.evictable_blocks

    @property
    def referenced_blocks(self) -> int:
        """Used blocks pinned by a live request (not reclaimable even by
        prefix eviction). free + evictable + referenced == pool blocks."""
        return self.pool.used_blocks - self.evictable_blocks

    @property
    def prefix_index_entries(self) -> int:
        return len(self._prefix)

    def fragmentation(self) -> dict:
        """Free / evictable / referenced split of the pool plus the
        prefix-index size — the first-class pool-state snapshot the
        engine summary and the metrics gauges both read."""
        return {
            "pool_free_blocks": self.pool.free_blocks,
            "pool_evictable_blocks": self.evictable_blocks,
            "pool_referenced_blocks": self.referenced_blocks,
            "prefix_index_entries": len(self._prefix),
        }

    def alloc_blocks(self, n: int) -> Optional[List[int]]:
        """``pool.alloc`` with LRU prefix eviction as the backstop: pop
        index entries (oldest first) whose block only the index holds —
        refcount-1 entries; blocks shared with live requests are never
        reclaimed — until the free list covers ``n``. An evicted parent
        makes its still-indexed children unreachable (the chained digest
        walk stops early); they age out of the LRU in turn. With a fleet
        store attached, the victim's device bytes are spilled into the
        store (digest-addressed, dedup'd) before the block is destroyed
        — eviction demotes the block a tier instead of forgetting it."""
        while self.pool.free_blocks < n:
            victim = None
            for dig, bid in self._prefix.items():
                if self.pool.refcount(bid) == 1:
                    victim = dig
                    break
            if victim is None:
                return None
            bid = self._prefix.pop(victim)
            if self.store is not None and self.spill_fn is not None:
                if self.spill_fn(victim, bid):
                    self.n_store_spills += 1
            self.pool.free([bid])
            self.n_prefix_evictions += 1
        return self.pool.alloc(n)
