"""Paged KV cache: a block pool with free-list allocation + host mirrors.

The device side is the flax cache collection the paged decode path
creates (``models/gpt.py _paged_decode_attention``): per-layer k/v pools
``[num_blocks, block_size, kvh, head_dim]`` (fp or int8 + scales), block
tables ``[slots, max_blocks]`` and lengths ``[slots]``. The pools are
the only *persistent* device state — tables and lengths are re-broadcast
from the host mirrors kept here before every jitted step, so all
scheduling (allocation, reclaim, preemption) is plain deterministic
Python with zero device syncs.

Block 0 is reserved as the null block: unallocated table entries point
at it, and the model's scatter redirects masked writes (prefill padding,
idle slots) there. Reads always mask by length, so its garbage is never
observed — this is what lets the scatter and the jitted step run
unpredicated over the full slot batch.
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np


class BlockPool:
    """Free-list allocator over ``num_blocks`` pool blocks (id 0 reserved).

    LIFO free list with deterministic order: the same request sequence
    always produces the same block ids — part of the engine's
    deterministic-replay contract.
    """

    def __init__(self, num_blocks: int):
        if num_blocks < 2:
            raise ValueError("need >= 2 blocks (block 0 is the null block)")
        self.num_blocks = num_blocks
        # pop() hands out ascending ids on a fresh pool.
        self._free: List[int] = list(range(num_blocks - 1, 0, -1))

    @property
    def free_blocks(self) -> int:
        return len(self._free)

    @property
    def used_blocks(self) -> int:
        return (self.num_blocks - 1) - len(self._free)

    @property
    def occupancy(self) -> float:
        return self.used_blocks / (self.num_blocks - 1)

    def alloc(self, n: int) -> Optional[List[int]]:
        """Allocate ``n`` blocks, or None (untouched pool) if short."""
        if n < 0:
            raise ValueError(f"alloc({n})")
        if n > len(self._free):
            return None
        return [self._free.pop() for _ in range(n)]

    def free(self, ids) -> None:
        for bid in ids:
            if not 0 < bid < self.num_blocks:
                raise ValueError(f"freeing invalid block id {bid}")
            if bid in self._free:
                raise ValueError(f"double free of block {bid}")
            self._free.append(bid)


class PagedKVCache:
    """Host mirrors (tables, lengths, pool) for one engine's slot batch."""

    def __init__(self, config, slots: int):
        if not config.decode_paged:
            raise ValueError("PagedKVCache needs config.decode_paged=True")
        self.config = config
        self.slots = slots
        self.block_size = config.paged_block_size
        self.max_blocks = config.paged_max_blocks
        self.pool = BlockPool(config.paged_num_blocks)
        self.tables = np.zeros((slots, self.max_blocks), np.int32)
        self.lengths = np.zeros((slots,), np.int32)
        self._n_blocks = np.zeros((slots,), np.int32)  # allocated per slot

    def blocks_for(self, n_tokens: int) -> int:
        """Blocks needed to hold ``n_tokens``."""
        return -(-n_tokens // self.block_size)

    def capacity_tokens(self) -> int:
        """Per-request token ceiling (the table width)."""
        return self.max_blocks * self.block_size

    def assign(self, slot: int, block_ids: List[int]) -> None:
        """Install a fresh allocation into an empty slot's table row."""
        assert self._n_blocks[slot] == 0, f"slot {slot} not released"
        n = len(block_ids)
        assert n <= self.max_blocks
        self.tables[slot, :n] = block_ids
        self._n_blocks[slot] = n

    def extend(self, slot: int, block_ids: List[int]) -> None:
        n0 = int(self._n_blocks[slot])
        n = len(block_ids)
        assert n0 + n <= self.max_blocks, f"slot {slot} table overflow"
        self.tables[slot, n0:n0 + n] = block_ids
        self._n_blocks[slot] = n0 + n

    def slot_blocks(self, slot: int) -> List[int]:
        return [int(b) for b in self.tables[slot, :self._n_blocks[slot]]]

    def release(self, slot: int) -> None:
        """Return a slot's blocks to the pool and null its table row."""
        self.pool.free(self.slot_blocks(slot))
        self.tables[slot] = 0
        self.lengths[slot] = 0
        self._n_blocks[slot] = 0
