"""Batched per-request sampling for the serving engine.

``generate_kv`` samples one shared (temperature, top_k) per call;
continuous batching puts requests with *different* sampling params in
one decode row-batch. This module samples the whole batch in one jitted
op with per-row temperature / top-k / top-p / PRNG key, and keys every
draw by ``fold_in(request_key, token_index)`` — the stream for a request
depends only on its own seed and position, NOT on which other requests
share the batch or how scheduling interleaved them. That independence is
what makes preemption recompute-safe (a resumed request re-derives the
exact draws it would have made) and replay deterministic.

``temperature == 0`` rows take exact greedy argmax (the same contract as
the fixed ``models/gpt.py _sample``), here as a data-dependent select
since temperature is a traced per-row array.

``filter_logits`` is the shared filtering pipeline (top-k at a static
``k_cap``, nucleus top-p over the temperature-scaled distribution,
temperature scale) — ``sample_tokens`` draws from it, and the
speculative-decode verifier (serving/spec.py) reuses it so acceptance
probabilities and residual draws see exactly the distribution the
non-speculative sampler would have drawn from.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


def filter_logits(
    logits: jax.Array,      # [b, vocab] f32
    temps: jax.Array,       # [b] f32; 0 = greedy (rows pass through)
    top_ks: jax.Array,      # [b] int32; 0 = no top-k filter
    top_ps: jax.Array,      # [b] f32; 1 = no nucleus filter
    *,
    k_cap: int,
) -> jax.Array:
    """Temperature-scaled logits with top-k then top-p applied per row.

    ``k_cap`` (static) bounds every row's top_k: one ``lax.top_k(logits,
    k_cap)`` serves all rows, each masking at its own kth value. Nucleus
    filtering keeps the smallest set of tokens whose cumulative
    (temperature-scaled) probability reaches ``top_p`` — boundary ties
    are all kept, and the top token always survives. Rows with ``top_p
    == 1`` skip the nucleus mask entirely, so pre-top-p streams are
    reproduced bit-for-bit.
    """
    b, vocab = logits.shape
    k_cap = max(1, min(k_cap, vocab))
    vals = jax.lax.top_k(logits, k_cap)[0]                 # [b, k_cap] desc
    k = jnp.clip(top_ks, 0, k_cap)
    kth = jnp.take_along_axis(
        vals, jnp.maximum(k - 1, 0)[:, None], axis=1)      # [b, 1]
    filtered = jnp.where(
        (k > 0)[:, None] & (logits < kth), -jnp.inf, logits)
    scaled = filtered / jnp.where(temps > 0, temps, 1.0)[:, None]
    p_lim = jnp.clip(top_ps, 0.0, 1.0)
    probs = jax.nn.softmax(scaled, axis=-1)
    sp = jax.lax.top_k(probs, vocab)[0]                    # [b, vocab] desc
    csum = jnp.cumsum(sp, axis=-1)
    # Keep a token when the mass strictly above it is still short of the
    # budget; the cutoff is the smallest kept probability.
    keep_n = jnp.maximum(
        jnp.sum((csum - sp) < p_lim[:, None], axis=-1), 1)
    cutoff = jnp.take_along_axis(sp, (keep_n - 1)[:, None], axis=1)
    return jnp.where(
        (p_lim < 1.0)[:, None] & (probs < cutoff), -jnp.inf, scaled)


@functools.partial(jax.jit, static_argnames=("k_cap",))
def sample_tokens(
    logits: jax.Array,      # [b, vocab] f32
    temps: jax.Array,       # [b] f32; 0 = greedy
    top_ks: jax.Array,      # [b] int32; 0 = no top-k filter
    top_ps: jax.Array,      # [b] f32; 1 = no nucleus filter
    key_data: jax.Array,    # [b, 2] uint32 per-request PRNG keys
    steps: jax.Array,       # [b] int32 token index within each request
    *,
    k_cap: int,
) -> jax.Array:
    """One token id per row. The engine derives k_cap from the requests
    it admits and recompiles only when a larger cap first appears."""
    scaled = filter_logits(logits, temps, top_ks, top_ps, k_cap=k_cap)
    sampled = jax.vmap(
        lambda kd, st, lg: jax.random.categorical(
            jax.random.fold_in(kd, st), lg)
    )(key_data, steps, scaled)
    return jnp.where(temps > 0, sampled, jnp.argmax(logits, axis=-1))


def request_key(seed: int):
    """The per-request key the engine stores host-side ([2] uint32)."""
    import numpy as np

    return np.asarray(jax.random.PRNGKey(seed))
