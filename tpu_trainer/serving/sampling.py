"""Batched per-request sampling for the serving engine.

``generate_kv`` samples one shared (temperature, top_k) per call;
continuous batching puts requests with *different* sampling params in
one decode row-batch. This module samples the whole batch in one jitted
op with per-row temperature / top-k / PRNG key, and keys every draw by
``fold_in(request_key, token_index)`` — the stream for a request depends
only on its own seed and position, NOT on which other requests share the
batch or how scheduling interleaved them. That independence is what
makes preemption recompute-safe (a resumed request re-derives the exact
draws it would have made) and replay deterministic.

``temperature == 0`` rows take exact greedy argmax (the same contract as
the fixed ``models/gpt.py _sample``), here as a data-dependent select
since temperature is a traced per-row array.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


@functools.partial(jax.jit, static_argnames=("k_cap",))
def sample_tokens(
    logits: jax.Array,      # [b, vocab] f32
    temps: jax.Array,       # [b] f32; 0 = greedy
    top_ks: jax.Array,      # [b] int32; 0 = no top-k filter
    key_data: jax.Array,    # [b, 2] uint32 per-request PRNG keys
    steps: jax.Array,       # [b] int32 token index within each request
    *,
    k_cap: int,
) -> jax.Array:
    """One token id per row. ``k_cap`` (static) bounds every row's top_k:
    one ``lax.top_k(logits, k_cap)`` serves all rows, each masking at its
    own kth value. The engine derives k_cap from the requests it admits
    and recompiles only when a larger cap first appears."""
    b, vocab = logits.shape
    k_cap = max(1, min(k_cap, vocab))
    vals = jax.lax.top_k(logits, k_cap)[0]                 # [b, k_cap] desc
    k = jnp.clip(top_ks, 0, k_cap)
    kth = jnp.take_along_axis(
        vals, jnp.maximum(k - 1, 0)[:, None], axis=1)      # [b, 1]
    filtered = jnp.where(
        (k > 0)[:, None] & (logits < kth), -jnp.inf, logits)
    scaled = filtered / jnp.where(temps > 0, temps, 1.0)[:, None]
    sampled = jax.vmap(
        lambda kd, st, lg: jax.random.categorical(
            jax.random.fold_in(kd, st), lg)
    )(key_data, steps, scaled)
    return jnp.where(temps > 0, sampled, jnp.argmax(logits, axis=-1))


def request_key(seed: int):
    """The per-request key the engine stores host-side ([2] uint32)."""
    import numpy as np

    return np.asarray(jax.random.PRNGKey(seed))
