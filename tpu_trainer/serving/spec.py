"""Speculative decoding: draft-propose / batch-verify over the paged KV
cache.

The serving engine's decode loop buys exactly one token per target-model
dispatch. Speculative decoding (Leviathan et al. 2023, "Fast Inference
from Transformers via Speculative Decoding") amortizes one target
forward over K cheaply drafted tokens: a proposer guesses the next K
tokens, the target scores all K+1 positions in ONE forward (reusing the
offset-aware in-flight+history branch of ``_paged_decode_attention`` —
the same machinery chunked prefill rides), and an acceptance rule keeps
the longest draft prefix the target agrees with plus one token the
target supplies itself. Every verify step therefore emits between 1 and
K+1 tokens at the cost of a single (slightly wider) dispatch.

Two proposers:

- ``NGramProposer`` — model-free prompt-lookup drafting (Saxena 2023):
  match the current context suffix against earlier context
  (prompt + generated) and propose the tokens that followed the most
  recent earlier occurrence. Free to compute, surprisingly effective on
  repetitive / extractive workloads, and ideal for this repo's
  CPU-testable bit-exactness-first ethos.
- ``DraftModelProposer`` — a small draft model (e.g. the target's first
  few scanned layers, ``draft_from_target``) decoding greedily over its
  OWN paged cache. The draft cache trails the true stream: each propose
  first catches up on tokens accepted since last time (one chunked feed
  at an offset — the draft reuses the very same engine step the target
  runs), then rolls K greedy decode steps forward. After verification
  the draft state rewinds to the accepted prefix.

Both proposers are DETERMINISTIC (a point-mass draft distribution),
which collapses the general two-model rejection-sampling rule to a
clean special case with the target distribution ``p`` (after the
request's temperature/top-k/top-p filtering, ``sampling.filter_logits``
— the exact distribution the non-speculative sampler draws from):

- greedy rows (``temperature == 0``): accept draft ``d_i`` iff it equals
  the target argmax at position i — so the accepted prefix plus the
  target's correction token IS the non-speculative greedy stream,
  bit for bit, no matter what the proposer guessed.
- sampled rows: accept ``d_i`` with probability ``p(d_i)`` (the
  ``min(1, p/q)`` rule with q a point mass); on rejection draw from the
  residual ``p`` with ``d_i`` masked out, renormalized; if every draft
  survives, draw the bonus token from ``p`` directly. The mixture
  ``p(d)·δ_d + (1 − p(d))·p|≠d`` is exactly ``p`` — the output
  distribution is unchanged, per the standard speculative-sampling
  argument. Draws are keyed by the engine's ``(seed, token_index)``
  scheme: the accept uniform for token index t is
  ``fold_in(fold_in(key, t), 1)``, the residual draw
  ``fold_in(fold_in(key, t), 2)``, and the bonus draw ``fold_in(key,
  t)`` — the same key the non-speculative sampler would use at that
  index.

``AdaptiveK`` shrinks the per-request draft length when the acceptance
EWMA drops (drafting costs a wider verify window and proposer work; on a
hostile stream K collapses to 1) and regrows it when drafts land.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from tpu_trainer.serving.paged_cache import PagedKVCache
from tpu_trainer.serving.sampling import filter_logits

# fold_in salts distinguishing the three draws made at one token index.
_SALT_ACCEPT = 1
_SALT_RESIDUAL = 2


# --- proposers --------------------------------------------------------------


class NGramProposer:
    """Prompt-lookup drafting: propose the continuation of the most
    recent earlier occurrence of the current context suffix, trying the
    longest n-gram first. Pure host-side Python over the token lists —
    no weights, no device work."""

    name = "ngram"

    def __init__(self, max_ngram: int = 3, min_ngram: int = 1):
        if not 1 <= min_ngram <= max_ngram:
            raise ValueError(f"ngram range [{min_ngram}, {max_ngram}]")
        self.max_ngram = max_ngram
        self.min_ngram = min_ngram

    def propose_one(self, context: List[int], k: int) -> List[int]:
        """Self-extending lookup: when a match's continuation runs out
        before ``k`` (the matched occurrence sits near the end — the
        short-period-cycle case), re-run the lookup with the draft so
        far appended, so a period-p loop drafts the full window."""
        out: List[int] = []
        ctx = list(context)
        while len(out) < k:
            nxt = self._lookup(ctx, k - len(out))
            if not nxt:
                break
            out.extend(nxt)
            ctx.extend(nxt)
        return out

    def _lookup(self, context: List[int], k: int) -> List[int]:
        if k <= 0 or len(context) < self.min_ngram + 1:
            return []
        for n in range(min(self.max_ngram, len(context) - 1),
                       self.min_ngram - 1, -1):
            suffix = context[-n:]
            # Most recent occurrence that ends strictly before the
            # suffix itself starts.
            for start in range(len(context) - n - 1, -1, -1):
                if context[start:start + n] == suffix:
                    cont = context[start + n:start + n + k]
                    if cont:
                        return [int(t) for t in cont]
        return []

    def propose(self, reqs, k_of: Dict[int, int]) -> Dict[int, List[int]]:
        return {r.rid: self.propose_one(r.prompt + r.generated,
                                        k_of[r.rid]) for r in reqs}

    def rewind(self, req, accepted: int) -> None:
        pass   # stateless


class DraftModelProposer:
    """Greedy draft-model proposer over its own paged cache.

    The draft pool is sized for every slot at full context, so draft
    allocation never fails and never preempts — scheduling pressure
    lives entirely in the target pool. Slot state is keyed by (slot,
    rid): a slot reused by a new request resets lazily, and a preempted
    request that resumes elsewhere simply re-feeds its stream (the
    stream is deterministic, so the rebuilt cache is identical).

    ``good[slot]`` counts the leading tokens of the TRUE stream whose
    K/V the draft cache holds; speculative feeds past it are rolled back
    by ``rewind`` after each verify (garbage K/V beyond ``good`` is
    never read — every dispatch masks by the lengths it passes)."""

    name = "draft"

    def __init__(self, draft_params, draft_config, *, slots: int,
                 block_size: int, attention: str = "auto"):
        from tpu_trainer.models.gpt import init_paged_cache

        mbpr = -(-draft_config.max_seq_len // block_size)
        self.config = dataclasses.replace(
            draft_config,
            dropout=0.0, attention_dropout=0.0,
            decode_paged=True, decode_ragged=False,
            paged_block_size=block_size,
            paged_num_blocks=slots * mbpr + 1,
            paged_max_blocks=mbpr,
            paged_kv_int8=False,
            paged_attention=attention,
        )
        self.params = draft_params
        self.slots = slots
        self.cache_state = PagedKVCache(self.config, slots)
        self.device_cache = init_paged_cache(self.config, slots)
        from tpu_trainer.serving.engine import _jitted_engine_step

        self._step_jit = _jitted_engine_step(self.config)
        self.good = np.zeros((slots,), np.int64)
        self.fed = np.zeros((slots,), np.int64)
        self.base = np.zeros((slots,), np.int64)
        self.slot_rid = -np.ones((slots,), np.int64)

    def _ensure_blocks(self, slot: int, n_tokens: int) -> None:
        cs = self.cache_state
        need = cs.blocks_for(n_tokens) - len(cs.slot_blocks(slot))
        if need > 0:
            got = cs.pool.alloc(need)
            assert got is not None, "draft pool sized for full contexts"
            cs.extend(slot, got)

    def _dispatch(self, reqs, ids, lengths, offsets, *, prefill,
                  hist_blocks, width):
        slots = self.slots
        tables = np.zeros_like(self.cache_state.tables)
        for r in reqs:
            tables[r.slot] = self.cache_state.tables[r.slot]
        zero_f = np.zeros((slots,), np.float32)
        one_f = np.ones((slots,), np.float32)
        zero_i = np.zeros((slots,), np.int32)
        keys = np.zeros((slots, 2), np.uint32)
        self.device_cache, tokens = self._step_jit(
            self.params, self.device_cache,
            jnp.asarray(tables), jnp.asarray(lengths),
            jnp.asarray(offsets), jnp.asarray(ids),
            zero_f, zero_i, one_f, keys, zero_i,
            k_cap=1, prefill=prefill, hist_blocks=hist_blocks,
        )
        return np.asarray(tokens)

    def propose(self, reqs, k_of: Dict[int, int]) -> Dict[int, List[int]]:
        from tpu_trainer.serving.engine import _bucket_pow2

        cs = self.cache_state
        for r in reqs:
            if self.slot_rid[r.slot] != r.rid:
                if cs.slot_blocks(r.slot):
                    cs.release(r.slot)
                self.slot_rid[r.slot] = r.rid
                self.good[r.slot] = 0
        max_m = max((k_of[r.rid] for r in reqs), default=0)
        if max_m <= 0:
            return {r.rid: [] for r in reqs}

        # Catch-up: feed each request's stream tokens the draft cache is
        # missing as one chunk at the cached offset — the exact chunked-
        # prefill contract the target engine uses.
        slots = self.slots
        feeds = {r.rid: r.context_len() - int(self.good[r.slot])
                 for r in reqs}
        width = min(_bucket_pow2(max(feeds.values()), lo=2),
                    cs.capacity_tokens())
        ids = np.zeros((slots, width), np.int32)
        lengths = np.zeros((slots,), np.int32)
        offsets = np.zeros((slots,), np.int32)
        max_hist = 0
        for r in reqs:
            stream = r.prompt + r.generated
            n_total = len(stream)
            cur = int(self.good[r.slot])
            self._ensure_blocks(r.slot, n_total + max_m - 1)
            ids[r.slot, :n_total - cur] = stream[cur:]
            lengths[r.slot] = n_total
            offsets[r.slot] = cur
            max_hist = max(max_hist, cur)
            self.base[r.slot] = n_total
            self.fed[r.slot] = n_total
        hist_blocks = 0
        if max_hist > 0:
            hist_blocks = min(
                _bucket_pow2(cs.blocks_for(max_hist), lo=1), cs.max_blocks)
        tokens = self._dispatch(reqs, ids, lengths, offsets, prefill=True,
                                hist_blocks=hist_blocks, width=width)
        proposals = {r.rid: [int(tokens[r.slot])] for r in reqs}

        # Roll forward: greedy single-token decode steps, feeding each
        # row its own previous draft.
        for t in range(1, max_m):
            ids1 = np.zeros((slots, 1), np.int32)
            lengths = np.zeros((slots,), np.int32)
            for r in reqs:
                ids1[r.slot, 0] = proposals[r.rid][-1]
                lengths[r.slot] = int(self.base[r.slot]) + t - 1
            tokens = self._dispatch(
                reqs, ids1, lengths, np.zeros((slots,), np.int32),
                prefill=False, hist_blocks=0, width=1)
            for r in reqs:
                proposals[r.rid].append(int(tokens[r.slot]))
                self.fed[r.slot] = int(self.base[r.slot]) + t
        return {r.rid: proposals[r.rid][:k_of[r.rid]] for r in reqs}

    def rewind(self, req, accepted: int) -> None:
        """Roll the draft cache back to the verified prefix: the first
        ``accepted`` drafts joined the true stream, anything fed beyond
        them is speculative garbage to overwrite on the next feed."""
        slot = req.slot
        if slot is None or self.slot_rid[slot] != req.rid:
            return
        self.good[slot] = min(self.base[slot] + accepted, self.fed[slot])


def draft_from_target(params, config, n_layers: int):
    """Cheap draft model: the target's FIRST ``n_layers`` scanned
    transformer layers with the embedding/norm shared (params['layers']
    leaves are stacked on axis 0). Zero extra training or storage — the
    classic truncated-self draft."""
    if not 1 <= n_layers < config.num_layers:
        raise ValueError(
            f"draft layers {n_layers} outside [1, {config.num_layers - 1}]")
    draft = dict(params)
    draft["layers"] = jax.tree_util.tree_map(
        lambda x: x[:n_layers], dict(params["layers"]))
    return draft, dataclasses.replace(config, num_layers=n_layers)


# --- adaptive draft length --------------------------------------------------


class AdaptiveK:
    """Per-request draft-length controller on an acceptance-rate EWMA:
    drafts dying (rate below ``low``) shrink K by one per step toward 1;
    drafts landing (rate above ``high``) regrow it toward ``k_max``."""

    def __init__(self, k_max: int, *, low: float = 0.3, high: float = 0.7,
                 alpha: float = 0.5):
        if k_max < 1:
            raise ValueError(f"k_max {k_max} < 1")
        self.k_max = k_max
        self.low = low
        self.high = high
        self.alpha = alpha
        self.k = k_max
        self.ewma = 1.0

    def update(self, drafted: int, accepted: int) -> int:
        if drafted > 0:
            rate = accepted / drafted
            self.ewma = (1 - self.alpha) * self.ewma + self.alpha * rate
            if self.ewma < self.low:
                self.k = max(1, self.k - 1)
            elif self.ewma > self.high:
                self.k = min(self.k_max, self.k + 1)
        return self.k


# --- the verifier -----------------------------------------------------------


def accept_emit(
    logits: jax.Array,      # [b, W, vocab] f32 — per-position target logits
    ids: jax.Array,         # [b, W] the fed window: [last token, drafts...]
    draft_lens: jax.Array,  # [b] true draft count per row (<= W-1)
    temps: jax.Array,       # [b]
    top_ks: jax.Array,      # [b]
    top_ps: jax.Array,      # [b]
    keys: jax.Array,        # [b, 2] uint32
    steps: jax.Array,       # [b] token index of the FIRST draw this step
    *,
    k_cap: int,
) -> Tuple[jax.Array, jax.Array]:
    """The acceptance rule, pure on logits (unit-testable without a
    model). Returns ``(emitted [b, W], n_acc [b])``: the host consumes
    ``emitted[:n_acc + 1]`` per row — accepted drafts followed by the
    target's correction (rejection) or bonus (all accepted) token."""
    b, w, vocab = logits.shape
    tgt = jnp.argmax(logits, axis=-1)                        # [b, W]
    scaled = filter_logits(
        logits.reshape(b * w, vocab),
        jnp.repeat(temps, w), jnp.repeat(top_ks, w),
        jnp.repeat(top_ps, w), k_cap=k_cap,
    ).reshape(b, w, vocab)
    probs = jax.nn.softmax(scaled, axis=-1)

    if w > 1:
        drafts = ids[:, 1:]                                  # [b, W-1]
        p_d = jnp.take_along_axis(
            probs[:, :-1], drafts[:, :, None], axis=-1)[..., 0]
        accept_u = jax.vmap(lambda kd, st: jax.vmap(
            lambda i: jax.random.uniform(jax.random.fold_in(
                jax.random.fold_in(kd, st + i), _SALT_ACCEPT))
        )(jnp.arange(w - 1)))(keys, steps)                   # [b, W-1]
        ok = jnp.where((temps > 0)[:, None],
                       accept_u < p_d, drafts == tgt[:, :-1])
        ok = ok & (jnp.arange(w - 1)[None, :] < draft_lens[:, None])
        n_acc = jnp.sum(jnp.cumprod(ok.astype(jnp.int32), axis=-1), axis=-1)
    else:
        n_acc = jnp.zeros((b,), jnp.int32)

    def draw_row(kd, st, row_scaled, row_ids, dlen):
        def one(i):
            kb = jax.random.fold_in(kd, st + i)
            bonus = jax.random.categorical(kb, row_scaled[i])
            if w == 1:
                return bonus
            # Residual draw for a rejection AT position i: the rejected
            # draft is row_ids[i + 1]; p with it masked, renormalized.
            d = row_ids[jnp.minimum(i + 1, w - 1)]
            resid = jnp.where(jnp.arange(vocab) == d, -jnp.inf,
                              row_scaled[i])
            rtok = jax.random.categorical(
                jax.random.fold_in(kb, _SALT_RESIDUAL), resid)
            return jnp.where(i < dlen, rtok, bonus)
        return jax.vmap(one)(jnp.arange(w))

    fix = jax.vmap(draw_row)(keys, steps, scaled, ids, draft_lens)
    iw = jnp.arange(w)[None, :]
    drafts_at = jnp.concatenate(
        [ids[:, 1:], jnp.zeros((b, 1), ids.dtype)], axis=1)  # draft at pos i
    emit_sampled = jnp.where(iw < n_acc[:, None], drafts_at, fix)
    emitted = jnp.where((temps > 0)[:, None], emit_sampled, tgt)
    return emitted, n_acc


def _verify_step(
    config, params, cache, tables, lengths, offsets, ids, draft_lens,
    temps, topks, topps, keys, steps, *, k_cap: int, hist_blocks: int,
):
    """One jitted verify step: broadcast host scheduling state into the
    cache pytree (same contract as ``engine._engine_step``), forward the
    [b, W] window through the chunked-prefill branch at each row's
    cached offset, keep ALL per-position logits, and run the acceptance
    rule in-graph — the host gets back tokens and counts, never a
    [b, W, vocab] logits transfer."""
    from tpu_trainer.models.gpt import GPT

    def put(path, x):
        key = getattr(path[-1], "key", None)
        if key == "tables":
            return jnp.broadcast_to(tables, x.shape)
        if key == "lengths":
            return jnp.broadcast_to(lengths, x.shape)
        if key == "offsets":
            return jnp.broadcast_to(offsets, x.shape)
        return x

    model = GPT(dataclasses.replace(config, paged_hist_blocks=hist_blocks))
    cache = jax.tree_util.tree_map_with_path(put, cache)
    if config.paged_tp > 1:
        # Sharded replica: exact params all-gather in, pool-layout
        # constraint out — same contract as engine._engine_step.
        from tpu_trainer.serving import sharding as tp_lib

        mesh = tp_lib.tp_mesh(config.paged_tp, config.paged_tp_devices)
        params = tp_lib.gather_params(params, mesh)
    (logits, _), vars_out = model.apply(
        {"params": params, "cache": cache}, ids, decode=True,
        mutable=["cache"],
    )
    cache_out = vars_out["cache"]
    if config.paged_tp > 1:
        cache_out = tp_lib.constrain_cache(cache_out, mesh, config.kv_heads)
    emitted, n_acc = accept_emit(
        logits.astype(jnp.float32), ids, draft_lens, temps, topks, topps,
        keys, steps, k_cap=k_cap)
    return cache_out, emitted, n_acc


# --- orchestration state ----------------------------------------------------


class SpecDecoder:
    """Host-side speculative-decode state for one engine: the proposer,
    per-request adaptive-K controllers, and the accepted-per-step
    histogram. The engine owns the device cache and the verify jit; this
    class owns everything that survives between steps."""

    def __init__(self, proposer, *, k: int, adaptive: bool = True):
        if k < 1:
            raise ValueError(f"spec_k {k} < 1")
        self.proposer = proposer
        self.k = k
        self.adaptive = adaptive
        self._ctl: Dict[int, AdaptiveK] = {}
        self.accept_hist: List[int] = []

    def k_for(self, req) -> int:
        """Draft budget for this request now: the adaptive controller's
        current K, capped so the window never drafts past max_new (an
        accepted draft + bonus may finish the request, but never
        overshoot it)."""
        k = self._ctl[req.rid].k if req.rid in self._ctl else self.k
        remaining = req.max_new_tokens - len(req.generated)
        return max(0, min(k, remaining - 1))

    def propose(self, reqs) -> Dict[int, List[int]]:
        k_of = {r.rid: self.k_for(r) for r in reqs}
        out = self.proposer.propose(reqs, k_of)
        return {rid: props[:k_of[rid]] for rid, props in out.items()}

    def observe(self, req, drafted: int, accepted: int) -> None:
        req.spec_drafted += drafted
        req.spec_accepted += accepted
        req.spec_steps += 1
        while len(self.accept_hist) <= accepted:
            self.accept_hist.append(0)
        self.accept_hist[accepted] += 1
        if self.adaptive and drafted > 0:
            ctl = self._ctl.setdefault(req.rid, AdaptiveK(self.k))
            ctl.update(drafted, accepted)
        self.proposer.rewind(req, accepted)

    def forget(self, req) -> None:
        """Drop per-request speculative state. Called on EVERY terminal
        transition — finish, preemption-free cancel, deadline expiry —
        so a cancelled request's draft-length controller (and, via the
        scheduler's vacate, its speculative KV tail blocks) can never
        leak: the proposer's slot mapping is keyed (slot, rid) and
        ``rewind`` guards against reuse, so forgetting here is the only
        cleanup a mid-speculation retire needs."""
        self._ctl.pop(req.rid, None)

    def reset_stats(self) -> None:
        self.accept_hist = []
