"""Offline tooling over training artifacts (JSONL runs, crash reports)."""
