"""Standalone mesh auto-planner CLI.

Prints the ranked plan table for a model preset and device count without
touching a trainer — pure shape arithmetic, so planning for a v5e-256 pod
from a laptop is instant:

    python -m tpu_trainer.tools.plan --model small --devices 8
    python -m tpu_trainer.tools.plan --model large --devices 256 \
        --device-kind v5e --hbm_gb 16 --strategy zero3

``--json`` emits the full ``kind:"mesh_plan"`` record (the same record a
``--mesh auto`` training run logs to JSONL) for scripting.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List, Optional

from tpu_trainer.models.config import GPTConfig
from tpu_trainer.parallel import planner


def _build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m tpu_trainer.tools.plan",
        description="Rank feasible data x fsdp x sequence x tensor x expert "
                    "x stage meshes for a model/pod from the analytic comms "
                    "+ roofline model.")
    p.add_argument("--model", default="small",
                   help="GPTConfig preset (small/medium/large/xl) or 'tiny'")
    p.add_argument("--devices", type=int, default=None,
                   help="device count to plan for (default: this process's "
                        "jax.device_count())")
    p.add_argument("--batch-size", type=int, default=8,
                   help="per-data-shard rows per micro-batch used to derive "
                        "the fixed global batch (default 8)")
    p.add_argument("--global-batch", type=int, default=None,
                   help="global rows per micro-batch held fixed across "
                        "candidates (default: batch-size * devices)")
    p.add_argument("--seq-len", type=int, default=None,
                   help="training sequence length (default: the model's "
                        "max_seq_len)")
    p.add_argument("--accum", type=int, default=1,
                   help="gradient accumulation steps (default 1)")
    p.add_argument("--strategy", default="zero3",
                   help="sharding strategy to plan under (default zero3)")
    p.add_argument("--num-experts", type=int, default=0,
                   help="make the FFNs MoE with this many experts (opens "
                        "the expert axis)")
    p.add_argument("--hbm_gb", "--hbm-gb", dest="hbm_gb", type=float,
                   default=None,
                   help="per-device HBM budget in GiB (default: local "
                        "device's bytes_limit; none on CPU)")
    p.add_argument("--device-kind", default="",
                   help="plan for this device kind's ICI/FLOPs tables "
                        "(e.g. v5e, v5p) instead of the local device")
    p.add_argument("--top-k", type=int, default=10,
                   help="rows in the ranked table (default 10)")
    p.add_argument("--json", action="store_true",
                   help="print the full mesh_plan record as JSON")
    return p


def _model_config(args) -> GPTConfig:
    extra = {}
    if args.seq_len:
        extra["max_seq_len"] = args.seq_len
    if args.num_experts:
        extra["num_experts"] = args.num_experts
        extra["moe_top_k"] = min(2, args.num_experts)
    if args.model == "tiny":
        return GPTConfig(vocab_size=256, hidden_size=64, num_layers=2,
                         num_heads=4, **extra)
    return GPTConfig.preset(args.model, **extra)


def main(argv: Optional[List[str]] = None) -> int:
    args = _build_parser().parse_args(argv)
    devices = args.devices
    if devices is None:
        import jax

        devices = jax.device_count()
    model_config = _model_config(args)
    seq_len = args.seq_len or model_config.max_seq_len
    global_rows = args.global_batch or args.batch_size * devices
    try:
        record = planner.plan(
            model_config, devices,
            global_rows=global_rows, max_seq_len=seq_len,
            grad_accum=args.accum, strategy=args.strategy,
            device_kind=args.device_kind, hbm_gb=args.hbm_gb,
            top_k=args.top_k)
    except planner.NoFeasiblePlanError as e:
        print(f"plan: {e}", file=sys.stderr)
        return 2
    if args.json:
        print(json.dumps(record))
    else:
        print("\n".join(planner.render_table(record)))
    return 0


if __name__ == "__main__":
    sys.exit(main())
