"""Offline run analyzer + regression gate over metrics JSONL.

    python -m tpu_trainer.tools.analyze run.jsonl
    python -m tpu_trainer.tools.analyze run.jsonl --compare base.jsonl

Turns the stream a training run (or bench.py) emits —
train/eval/goodput/telemetry/cost_analysis/comms_model/recompile/rollback
records — into a human report: step-time percentiles, tok/s stability,
the goodput table, spike/rollback/recompile events, and the comms share
of the step. ``serve`` records (benchmarks/serve_bench.py) and ``decode``
records (benchmarks/decode_bench.py) fold into the same report, so one
file can carry a whole train+serve CI run. The elastic supervisor's
``supervisor.jsonl`` (``host_death`` / ``recovery`` / ``world_grow`` /
``elastic_summary`` records, see training/elastic.py) folds in too: the
report shows each restart's detection-to-first-step recovery time and
each grow-back's grant-to-first-grown-step time. With ``--compare`` it
renders PASS/FAIL verdicts for the new run against a baseline run on
throughput, MFU, peak HBM, final loss, serving tok/s and p99 tail
latency, and decode-path tok/s — plus four elastic gates: ABSOLUTE caps
on per-restart recovery seconds (``--recovery-tol``) and per-grow
re-expansion seconds (``--grow-tol``), a restart-count-regression check,
and a failure-to-regrow check (an ``--allow_grow`` run that lost hosts
must finish back at its desired world). ``frontend`` records
(``serve_bench --replicas``, the multi-replica front-end) add two more:
an ABSOLUTE admission-reject ceiling (``--reject-tol``) and a
categorical affinity-vs-random prefix-hit-rate check over the same
``--ab`` run. It exits nonzero on any FAIL —
a CI-usable gate over the bench trajectory (exit 0 clean, 1 regression,
2 unreadable/mis-schema'd input).

Every record must carry the ``schema_version`` stamp MetricLogger writes;
unversioned or mismatched records abort with exit 2 so old runs fail
loudly instead of misparsing.
"""

from __future__ import annotations

import argparse
import json
import math
import sys
from typing import Dict, List, Optional

from tpu_trainer.utils.logging import SCHEMA_VERSION


class SchemaError(ValueError):
    """A JSONL line the analyzer refuses to interpret."""


def load_records(path: str) -> List[dict]:
    """Parse one record per line, enforcing the schema_version stamp."""
    records = []
    with open(path) as fh:
        for ln, line in enumerate(fh, 1):
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError as e:
                raise SchemaError(f"{path}:{ln}: not valid JSON ({e})")
            if not isinstance(rec, dict):
                raise SchemaError(f"{path}:{ln}: record is not an object")
            version = rec.get("schema_version")
            if version is None:
                raise SchemaError(
                    f"{path}:{ln}: record (kind={rec.get('kind')!r}) carries "
                    f"no schema_version — this run predates the stamped "
                    f"JSONL schema; re-run it under the current trainer")
            if version != SCHEMA_VERSION:
                raise SchemaError(
                    f"{path}:{ln}: schema_version {version!r} != supported "
                    f"{SCHEMA_VERSION}")
            records.append(rec)
    if not records:
        raise SchemaError(f"{path}: no records")
    return records


def _percentile(xs: List[float], q: float) -> Optional[float]:
    if not xs:
        return None
    xs = sorted(xs)
    if len(xs) == 1:
        return xs[0]
    pos = q / 100.0 * (len(xs) - 1)
    lo = int(math.floor(pos))
    hi = min(lo + 1, len(xs) - 1)
    return xs[lo] + (xs[hi] - xs[lo]) * (pos - lo)


def _stats(xs: List[float]) -> Optional[dict]:
    xs = [x for x in xs if x is not None and math.isfinite(x)]
    if not xs:
        return None
    mean = sum(xs) / len(xs)
    var = sum((x - mean) ** 2 for x in xs) / len(xs)
    return {
        "n": len(xs),
        "mean": mean,
        "p10": _percentile(xs, 10),
        "p50": _percentile(xs, 50),
        "p90": _percentile(xs, 90),
        "cv": math.sqrt(var) / mean if mean else None,
    }


def summarize(records: List[dict]) -> dict:
    """Reduce a record stream to the report dict ``render`` prints and
    ``compare`` gates on."""
    by_kind: Dict[str, List[dict]] = {}
    for rec in records:
        by_kind.setdefault(str(rec.get("kind")), []).append(rec)

    report: dict = {"n_records": len(records)}

    train = sorted(by_kind.get("train", []), key=lambda r: r.get("step", 0))
    # Drop the first record: it absorbs compile time, and every steady-state
    # statistic (and the compare gate) should see the post-warmup run.
    steady = train[1:] if len(train) > 2 else train
    if train:
        losses = [r.get("loss") for r in steady if r.get("loss") is not None]
        step_times = []
        for a, b in zip(train, train[1:]):
            ds = b.get("step", 0) - a.get("step", 0)
            dt = (b.get("elapsed_s") or 0) - (a.get("elapsed_s") or 0)
            if ds > 0 and dt > 0:
                step_times.append(dt / ds)
        report["train"] = {
            "steps": [train[0].get("step"), train[-1].get("step")],
            "final_loss": (_percentile(losses[-5:], 50) if losses else None),
            "tok_per_sec": _stats(
                [r.get("tokens_per_sec") for r in steady]),
            "step_time_s": _stats(step_times[1:] or step_times),
            "mfu": _stats([r.get("mfu") for r in steady
                           if r.get("mfu") is not None]),
            "peak_mem_gb": max(
                (r["peak_mem_gb"] for r in train if r.get("peak_mem_gb")),
                default=None),
        }

    evals = by_kind.get("eval", [])
    if evals:
        report["eval"] = {
            "final_loss": evals[-1].get("eval_loss"),
            "final_perplexity": evals[-1].get("perplexity"),
            "n": len(evals),
        }

    goodput = by_kind.get("goodput", [])
    if goodput:
        final = [g for g in goodput if g.get("final")] or goodput
        g = final[-1]
        report["goodput"] = {
            "total_seconds": g.get("total_seconds"),
            "productive_frac": g.get("productive_frac"),
            "fractions": {
                k[:-len("_frac")]: v for k, v in sorted(g.items())
                if k.endswith("_frac")
                # (the ledger's token ratio is non_pad_token_ratio,
                # deliberately outside this namespace; "packing" below)
                and k not in ("productive_frac", "untracked_frac")
            },
            "untracked_frac": g.get("untracked_frac"),
        }

    # Sequence-packing efficiency: the loader-side cumulative non-pad token
    # fraction rides the train records (MetricLogger.non_pad_frac) and the
    # goodput ledger; cumulative → the last record is the run's number.
    pack_fracs = [r.get("non_pad_frac") for r in train
                  if r.get("non_pad_frac") is not None]
    ledger_frac = None
    if goodput:
        final = [g2 for g2 in goodput if g2.get("final")] or goodput
        ledger_frac = final[-1].get("non_pad_token_ratio")
    if pack_fracs or ledger_frac is not None:
        report["packing"] = {
            "non_pad_frac": (pack_fracs[-1] if pack_fracs else ledger_frac),
            "ledger_non_pad_frac": ledger_frac,
            "effective_tok_per_sec": _stats(
                [r.get("effective_tokens_per_sec") for r in steady
                 if r.get("effective_tokens_per_sec") is not None]),
        }

    comms = by_kind.get("comms_model", [])
    if comms:
        c = comms[-1]
        report["comms"] = {
            "mesh": c.get("mesh"),
            "strategy": c.get("strategy"),
            "total_bytes_per_device_per_step":
                c.get("total_bytes_per_device_per_step"),
            "per_axis_bytes": {
                axis: info.get("bytes")
                for axis, info in (c.get("per_axis") or {}).items()
                if info.get("bytes")},
            "comms_seconds_est": c.get("comms_seconds_est"),
            "compute_seconds_est": c.get("compute_seconds_est"),
            "comms_compute_ratio": c.get("comms_compute_ratio"),
            "bound": c.get("bound"),
            "hlo_mismatches": c.get("hlo_mismatches"),
        }

    # Mesh auto-planner validation loop (parallel/planner.py): the
    # mesh_plan record carries the chosen split and its predicted step
    # time; bench train records carry a per-window plan_error_frac, whose
    # MEDIAN is the number the --plan-tol gate prices. A run with train
    # windows but no mesh_plan record (training CLI --mesh auto runs log
    # the plan but never a measured step-ms) still reports the plan.
    plans = by_kind.get("mesh_plan", [])
    plan_errors = [r.get("plan_error_frac") for r in train
                   if r.get("plan_error_frac") is not None]
    if plans:
        p = plans[-1]
        chosen = p.get("chosen") or {}
        report["plan"] = {
            "auto": p.get("auto"),
            "mesh": chosen.get("mesh"),
            "strategy": p.get("strategy"),
            "batch_per_shard": chosen.get("batch_per_shard"),
            "n_enumerated": p.get("n_enumerated"),
            "n_feasible": p.get("n_feasible"),
            "pruned": p.get("pruned"),
            "predicted_step_ms": p.get("predicted_step_ms"),
            "measured_step_ms": p.get("measured_step_ms"),
            "plan_error_frac": (_percentile(plan_errors, 50)
                                if plan_errors
                                else p.get("plan_error_frac")),
            "bound": chosen.get("bound"),
            "predicted_peak_hbm_gb": chosen.get("peak_hbm_gb"),
        }

    cost = by_kind.get("cost_analysis", [])
    if cost:
        report["cost"] = {k: cost[-1].get(k) for k in (
            "xla_flops_per_step", "analytic_flops_per_step",
            "xla_peak_bytes") if cost[-1].get(k) is not None}

    recompiles = by_kind.get("recompile", [])
    if recompiles:
        report["recompiles"] = {
            "count": len(recompiles),
            "steps": [r.get("step") for r in recompiles],
            "shapes": sorted({str(r.get("batch_abstract"))
                              for r in recompiles}),
            "storm": any(r.get("storm") for r in recompiles),
        }

    rollbacks = by_kind.get("rollback", [])
    if rollbacks:
        report["rollbacks"] = [{
            "step": r.get("step"),
            "cause": r.get("cause"),
            "restored_step": r.get("restored_step"),
        } for r in rollbacks]

    serves = by_kind.get("serve", [])
    if serves:
        # serve_bench.py records: last one wins (a file may accumulate
        # runs; the newest reflects the current tree).
        s = serves[-1]
        report["serve"] = {k: s.get(k) for k in (
            "tokens_per_s", "ttft_p50_s", "ttft_p99_s", "tpot_p50_s",
            "tpot_p99_s", "queue_wait_p50_s", "queue_wait_p99_s",
            "occupancy_mean", "occupancy_max", "preemptions",
            "sequential_tokens_per_s", "concurrent_speedup", "n_requests",
            "concurrency", "workload", "lane", "prefill_chunk",
            "prefix_cache", "prefill_chunks", "prefix_hit_rate",
            "prefix_hit_tokens", "prompt_tokens",
            "prefix_evictions", "spec", "spec_k", "spec_steps",
            "spec_drafted", "spec_accepted", "spec_accept_mean",
            "spec_accept_rate", "spec_accept_hist",
            "tp", "device_pool_blocks", "total_pool_blocks",
            "peak_pool_blocks", "wire_bytes_per_worker", "wire_ratio",
            "tp_token_match",
            ) if s.get(k) is not None}

    fronts = by_kind.get("frontend", [])
    if fronts:
        # serve_bench.py --replicas records (the multi-replica front-end,
        # serving/frontend.py). Latest record wins for the summary line;
        # the routing A/B is read from whichever record carries its own
        # random baseline (serve_bench --ab annotates the policy lane),
        # falling back to pairing this file's newest policy and random
        # lanes.
        f = fronts[-1]
        report["frontend"] = {k: f.get(k) for k in (
            "workload", "lane", "routing", "replicas", "replicas_live",
            "tokens_per_s", "ttft_p99_s", "submitted", "accepted",
            "queue_wait_p50_s", "queue_wait_p99_s",
            "rejected", "reject_rate", "prefix_hit_rate",
            "load_imbalance_mean", "load_imbalance_max",
            "failover_events", "failed_over_requests", "wait_age_p99_s",
            "transport", "workers", "worker_deaths",
            "finished", "cancelled", "deadline_exceeded",
            "tp", "device_pool_blocks", "total_pool_blocks",
            "wire_bytes_per_worker", "wire_ratio", "tp_token_match",
            "fleet_prefix_hit_rate", "store_hit_tokens",
            "store_hit_tokens_host", "store_hit_tokens_disk",
            "migrations", "migrated_bytes",
            "baseline_prefix_hit_rate", "disagg_token_match",
            ) if f.get(k) is not None}

    # Sharded-decode (tensor-parallel) parity: EVERY record that carries
    # a tp_token_match verdict counts — the bench stamps one per lane
    # compared against the unsharded / no-fault base lane, so one
    # mismatch anywhere in the file is a real divergence, not noise the
    # newest record should shadow.
    tp_recs = [r for r in serves + fronts
               if r.get("tp_token_match") is not None]
    if tp_recs:
        bad = [r.get("lane") for r in tp_recs if not r["tp_token_match"]]
        report["tp_parity"] = {
            "tp": max(int(r.get("tp") or 0) for r in tp_recs),
            "records": len(tp_recs),
            "mismatched": len(bad),
            "mismatched_lanes": bad,
        }
        # Lifecycle / chaos metrics (deadline misses, hung-RPC stalls,
        # fence counts) live on whichever lane carried the deadline or
        # fault — scan for the newest record with each, like the RPC
        # overhead scan below.
        for k in ("deadline_miss_rate", "deadline_miss_slack_p50",
                  "deadline_miss_slack_p99", "stall_recovery_max_s",
                  "fenced"):
            r = next((x for x in reversed(fronts)
                      if x.get(k) is not None), None)
            if r is not None:
                report["frontend"][k] = r.get(k)
        # The RPC-overhead fields live on the cross-process A/B lane's
        # record, which may not be the newest (a worker_kill lane often
        # follows it) — scan for the newest rpc-transport record.
        rpc = next((r for r in reversed(fronts)
                    if r.get("transport") == "rpc"
                    and r.get("rpc_overhead_p99_s") is not None),
                   None) or next((r for r in reversed(fronts)
                                  if r.get("transport") == "rpc"), None)
        if rpc is not None:
            for k in ("rpc_overhead_p50_s", "rpc_overhead_p99_s",
                      "tok_s_vs_inproc", "inproc_tokens_per_s"):
                if rpc.get(k) is not None:
                    report["frontend"][k] = rpc.get(k)
            report["frontend"]["transport"] = "rpc"
            report["frontend"]["workers"] = rpc.get("workers")
            report["frontend"]["worker_deaths"] = max(
                int(r.get("worker_deaths") or 0) for r in fronts)
        ab = next((r for r in reversed(fronts)
                   if r.get("random_prefix_hit_rate") is not None), None)
        if ab is None:
            aff = next((r for r in reversed(fronts)
                        if r.get("routing") != "random"
                        and r.get("lane") != "replica_kill"), None)
            rnd = next((r for r in reversed(fronts)
                        if r.get("routing") == "random"), None)
            if aff is not None and rnd is not None:
                ab = dict(aff,
                          random_prefix_hit_rate=rnd.get("prefix_hit_rate"))
        if ab is not None:
            report["frontend"]["ab"] = {
                "routing": ab.get("routing"),
                "prefix_hit_rate": ab.get("prefix_hit_rate"),
                "random_prefix_hit_rate": ab.get("random_prefix_hit_rate"),
                "tok_s_vs_random": ab.get("tok_s_vs_random"),
            }

    # Disaggregated-serving / fleet-KV-store metrics live on whichever
    # lane ran with the store (serve_bench --disagg stamps the disagg
    # lane; a plain kv_store lane carries store counters too) — the
    # newest store-bearing record wins the summary so a later storeless
    # lane can't shadow it. Like tp parity, the migrated-stream verdict
    # counts EVERY record carrying one: a single migrated stream that
    # diverged from the single-engine pin is a real divergence, not
    # noise the newest record should hide.
    dis_recs = [r for r in fronts
                if r.get("disagg_token_match") is not None
                or r.get("migrations") or r.get("store_hit_tokens")]
    if dis_recs:
        d = dis_recs[-1]
        pinned = [r for r in fronts
                  if r.get("disagg_token_match") is not None]
        bad = [r.get("lane") for r in pinned
               if not r["disagg_token_match"]]
        report["disagg"] = {k: d.get(k) for k in (
            "lane", "workload", "routing",
            "fleet_prefix_hit_rate", "baseline_prefix_hit_rate",
            "store_hit_tokens", "store_hit_tokens_host",
            "store_hit_tokens_disk", "migrations", "migrated_bytes",
            ) if d.get(k) is not None}
        report["disagg"]["records"] = len(pinned)
        report["disagg"]["mismatched"] = len(bad)
        report["disagg"]["mismatched_lanes"] = bad
        roles = [p.get("role") for p in (d.get("per_replica") or ())]
        if any(roles):
            report["disagg"]["roles"] = roles

    # Serve-timeline section: kind:"span" records (serving/tracing.py,
    # emitted by serve_bench per finished rid). Phase percentiles and the
    # worst-p99 waterfall read the LATEST lane's spans (lanes differ in
    # chunking/spec config, so mixing them would muddy the tail), while
    # span conservation is checked over EVERY span in the file — a
    # dropped terminal event is a loss regardless of which lane dropped
    # it. Each record carries its own event list, so conservation is per
    # record (rids repeat across lanes; cross-record grouping would
    # false-positive on the collision).
    spans = by_kind.get("span", [])
    if spans:
        last_lane = spans[-1].get("lane")
        lane_spans = [r for r in spans if r.get("lane") == last_lane]
        open_rids, multi = [], []
        for r in spans:
            kinds = [e.get("event") for e in (r.get("events") or ())]
            if "rejected" in kinds:
                continue
            if not any(k in ("submitted", "admitted") for k in kinds):
                continue
            n_term = sum(1 for k in kinds if k in (
                "finished", "cancelled", "deadline_exceeded", "failed"))
            if n_term > 1:
                multi.append(r.get("rid"))
            elif n_term == 0 and "exported" not in kinds:
                open_rids.append(r.get("rid"))
        phases = {}
        for name in ("queue_wait", "prefill", "decode", "total"):
            vals = [r.get(f"{name}_s") for r in lane_spans
                    if r.get(f"{name}_s") is not None]
            if vals:
                phases[name] = {
                    "n": len(vals),
                    "p50": _percentile(vals, 50),
                    "p99": _percentile(vals, 99),
                }
        worst = sorted(
            (r for r in lane_spans if r.get("total_s") is not None),
            key=lambda r: r["total_s"], reverse=True)[:3]
        report["spans"] = {
            "n": len(spans),
            "lane": last_lane,
            "conservation_ok": not open_rids and not multi,
            "open": open_rids[:10],
            "multi_terminal": multi[:10],
            "phases": phases,
            "waterfall": [{k: r.get(k) for k in (
                "rid", "replica", "queue_wait_s", "prefill_s",
                "decode_s", "total_s", "n_events")} for r in worst],
        }

    # Fleet time series: kind:"serve_ts" samples (ServingLedger.record).
    # Same latest-lane convention as the span percentiles.
    ts = by_kind.get("serve_ts", [])
    if ts:
        last_lane = ts[-1].get("lane")
        lane_ts = [r for r in ts if r.get("lane") == last_lane]
        final = next((r for r in reversed(lane_ts) if r.get("final")),
                     lane_ts[-1])
        depths = [r.get("queue_depth") for r in lane_ts
                  if r.get("queue_depth") is not None]
        report["serve_ts"] = {
            "n": len(lane_ts),
            "lane": last_lane,
            "total_seconds": final.get("total_seconds"),
            "dispatch_frac": final.get("dispatch_frac"),
            "host_sched_frac": final.get("host_sched_frac"),
            "rpc_wait_frac": final.get("rpc_wait_frac"),
            "idle_frac": final.get("idle_frac"),
            "untracked_frac": final.get("untracked_frac"),
            "queue_depth": _stats([float(d) for d in depths]),
            "queue_depth_series": [float(d) for d in depths],
            "outstanding_tokens": _stats(
                [float(r["outstanding_tokens"]) for r in lane_ts
                 if r.get("outstanding_tokens") is not None]),
            "occupancy": _stats(
                [float(r["occupancy"]) for r in lane_ts
                 if r.get("occupancy") is not None]),
        }

    # Incidents: fence/failover/worker-death/drain-failure markers from
    # the serving flight recorder (frontend._dump_incident).
    incidents = by_kind.get("incident", [])
    if incidents:
        by_reason: Dict[str, int] = {}
        for r in incidents:
            by_reason[str(r.get("reason"))] = (
                by_reason.get(str(r.get("reason")), 0) + 1)
        report["incidents"] = {
            "n": len(incidents),
            "by_reason": by_reason,
            "dumps": [r.get("dump_dir") for r in incidents
                      if r.get("dump_dir")],
        }

    decodes = by_kind.get("decode", [])
    if decodes:
        rows = decodes[-1].get("rows") or []
        paths: Dict[str, float] = {}
        for r in rows:
            key = f"{r.get('path')}/bs{r.get('batch')}"
            tps = r.get("tok_per_sec")
            if tps is not None:
                paths[key] = max(paths.get(key, 0.0), float(tps))
        kv_best = max((v for k, v in paths.items() if k.startswith("kv/")),
                      default=None)
        report["decode"] = {"paths": paths, "kv_best_tok_per_sec": kv_best}

    deaths = by_kind.get("host_death", [])
    recoveries = by_kind.get("recovery", [])
    grows = by_kind.get("world_grow", [])
    esummary = by_kind.get("elastic_summary", [])
    if deaths or recoveries or grows or esummary:
        rec_secs = [r.get("recovery_seconds") for r in recoveries
                    if r.get("recovery_seconds") is not None]
        grow_secs = [g.get("grow_seconds") for g in grows
                     if g.get("grow_seconds") is not None]
        summary = esummary[-1] if esummary else {}
        report["elastic"] = {
            "restarts": summary.get("restarts", len(recoveries)),
            "final_world": summary.get("final_world"),
            "desired_world": summary.get("desired_world"),
            "allow_grow": summary.get("allow_grow"),
            "supervisor_exit_code": summary.get("exit_code"),
            "deaths": [{"host": d.get("host"), "cause": d.get("cause")}
                       for d in deaths],
            "proactive_drains": sum(1 for d in deaths if d.get("proactive")),
            "recovery_seconds": rec_secs,
            "recovery_seconds_total": summary.get(
                "recovery_seconds_total", sum(rec_secs) or None),
            "recovery_seconds_max": max(rec_secs, default=None),
            "rolled_back_steps": [r.get("rolled_back_steps")
                                  for r in recoveries],
            "standby_promotions": summary.get("standby_promotions"),
            "worlds": [[r.get("world_before"), r.get("world_after")]
                       for r in recoveries],
            "grows": summary.get("grows", len(grows)),
            "grow_seconds": grow_secs,
            "grow_seconds_total": summary.get(
                "grow_seconds_total", sum(grow_secs) or None),
            "grow_seconds_max": max(grow_secs, default=None),
            "grow_worlds": [[g.get("world_before"), g.get("world_after")]
                            for g in grows],
        }

    # Per-source loss: mixture runs tag each train record with the source
    # that produced its batch (``data_source``), so one mixed run yields a
    # loss curve per corpus — the signal mixture weights are tuned from.
    by_src: Dict[str, List[float]] = {}
    for r in train:
        src = r.get("data_source")
        if src is not None and r.get("loss") is not None:
            by_src.setdefault(str(src), []).append(float(r["loss"]))
    if by_src:
        report["sources"] = {
            src: {
                "n": len(ls),
                "loss": _stats(ls),
                "final_loss": _percentile(ls[-5:], 50),
            }
            for src, ls in sorted(by_src.items())
        }

    telemetry_steps = [r.get("step") for r in train
                       if any(k.startswith("telemetry/") for k in r)]
    if telemetry_steps:
        report["telemetry_steps"] = len(telemetry_steps)

    # MoE router health.  models/moe.py records per-layer router stats that
    # utils/telemetry.flatten_scalars spreads into
    # ``telemetry/router/<key>/L..`` train-record scalars: ``entropy``
    # (routing distribution), ``drop_frac`` (tokens past capacity — 0 by
    # construction under moe_impl="dropless"), ``max_group_frac`` (largest
    # expert's share of routed tokens; 1/E is perfectly balanced, ~1.0 is a
    # collapsed router), and a ``dropless`` 0/1 marker.  drop_frac and
    # max_group_frac aggregate as max-over-layers per record so one bad
    # layer can't hide behind healthy siblings.
    def _router_vals(rec: dict, key: str) -> List[float]:
        pfx = f"telemetry/router/{key}/"
        return [float(v) for k, v in rec.items() if k.startswith(pfx)]

    router_recs = [r for r in train
                   if any(k.startswith("telemetry/router/") for k in r)]
    if router_recs:
        drops = [max(_router_vals(r, "drop_frac") or [0.0])
                 for r in router_recs]
        imbal = [max(_router_vals(r, "max_group_frac") or [0.0])
                 for r in router_recs]
        last_entropy = _router_vals(router_recs[-1], "entropy")
        dl_marks = _router_vals(router_recs[-1], "dropless")
        report["router"] = {
            "n": len(router_recs),
            "dropless": bool(dl_marks) and min(dl_marks) >= 0.5,
            "entropy": _stats(last_entropy),
            "drop_frac": _stats(drops),
            "drop_frac_max": max(drops) if drops else None,
            "max_group_frac": _stats(imbal),
        }
    return report


def _fmt(x, nd=2, default="-"):
    if x is None:
        return default
    if isinstance(x, float):
        return f"{x:,.{nd}f}"
    return str(x)


_SPARK = "▁▂▃▄▅▆▇█"


def _sparkline(xs: List[float], width: int = 32) -> str:
    """Down-sampled unicode sparkline of a series (mean per bucket)."""
    xs = [x for x in xs if x is not None and math.isfinite(x)]
    if not xs:
        return ""
    if len(xs) > width:
        per = len(xs) / width
        xs = [sum(xs[int(i * per):max(int(i * per) + 1, int((i + 1) * per))])
              / max(1, len(xs[int(i * per):max(int(i * per) + 1,
                                               int((i + 1) * per))]))
              for i in range(width)]
    lo, hi = min(xs), max(xs)
    span = (hi - lo) or 1.0
    return "".join(
        _SPARK[min(len(_SPARK) - 1,
                   int((x - lo) / span * (len(_SPARK) - 1)))] for x in xs)


def render(report: dict) -> List[str]:
    """Human report lines."""
    lines = [f"== run analysis ({report['n_records']} records) =="]
    t = report.get("train")
    if t:
        lines.append(f"steps {t['steps'][0]}..{t['steps'][1]}"
                     f" | final loss {_fmt(t['final_loss'], 4)}")
        tok = t.get("tok_per_sec")
        if tok:
            lines.append(
                f"tok/s   p10 {_fmt(tok['p10'], 0)}  p50 {_fmt(tok['p50'], 0)}"
                f"  p90 {_fmt(tok['p90'], 0)}  cv {_fmt(tok['cv'], 3)}")
        st = t.get("step_time_s")
        if st:
            lines.append(
                f"step_s  p10 {_fmt(st['p10'], 4)}  p50 {_fmt(st['p50'], 4)}"
                f"  p90 {_fmt(st['p90'], 4)}")
        if t.get("mfu"):
            lines.append(f"mfu     p50 {_fmt(t['mfu']['p50'], 4)}")
        if t.get("peak_mem_gb") is not None:
            lines.append(f"peak HBM {_fmt(t['peak_mem_gb'])} GB")
    else:
        lines.append("no train records")
    e = report.get("eval")
    if e:
        lines.append(f"eval    loss {_fmt(e['final_loss'], 4)}"
                     f"  ppl {_fmt(e['final_perplexity'])} ({e['n']} evals)")
    g = report.get("goodput")
    if g:
        fr = "  ".join(f"{k} {_fmt(v * 100, 1)}%"
                       for k, v in g["fractions"].items())
        lines.append(f"goodput {_fmt((g.get('productive_frac') or 0) * 100, 1)}%"
                     f" productive over {_fmt(g['total_seconds'], 1)}s"
                     f" | {fr}"
                     f" | untracked {_fmt((g.get('untracked_frac') or 0) * 100, 1)}%")
    p = report.get("packing")
    if p:
        eff = p.get("effective_tok_per_sec")
        eff_s = (f" | effective tok/s p50 {_fmt(eff['p50'], 0)}"
                 if eff else "")
        lines.append(
            f"packing non-pad frac {_fmt(p['non_pad_frac'], 4)}{eff_s}")
    c = report.get("comms")
    if c:
        axes = "  ".join(f"{k} {_fmt(v / 1e6, 1)}MB"
                         for k, v in c["per_axis_bytes"].items())
        lines.append(
            f"comms   {_fmt((c.get('total_bytes_per_device_per_step') or 0) / 1e6, 1)}"
            f" MB/device/step ({axes or 'none'})"
            f" | est comms/compute {_fmt(c.get('comms_compute_ratio'))}"
            f" -> {c.get('bound')}-bound")
        for m in c.get("hlo_mismatches") or []:
            lines.append(f"comms   HLO mismatch: {m}")
    pl = report.get("plan")
    if pl:
        mesh_s = ("x".join(str(v) for v in (pl.get("mesh") or {}).values())
                  or "?")
        err = pl.get("plan_error_frac")
        lines.append(
            f"plan    {'auto ' if pl.get('auto') else ''}mesh {mesh_s}"
            f" ({pl.get('strategy')}, batch/shard"
            f" {pl.get('batch_per_shard')})"
            + (f" | {pl['n_feasible']}/{pl['n_enumerated']} feasible"
               if pl.get("n_enumerated") else "")
            + f" | predicted {_fmt(pl.get('predicted_step_ms'))}ms"
            + (f" measured {_fmt(pl.get('measured_step_ms'))}ms"
               if pl.get("measured_step_ms") is not None else "")
            + (f" | median err {_fmt(err * 100, 1)}%"
               if err is not None else "")
            + (f" -> {pl.get('bound')}-bound" if pl.get("bound") else ""))
    ro = report.get("router")
    if ro:
        ent = ro.get("entropy")
        drop = ro.get("drop_frac")
        imbal = ro.get("max_group_frac")
        flag = ""
        if ro.get("dropless") and drop and drop["p90"] > 0:
            flag = "  ** TOKENS DROPPED ON DROPLESS RUN **"
        lines.append(
            f"router  {'dropless' if ro.get('dropless') else 'capacity'}"
            f" | entropy p50 {_fmt(ent['p50'], 3) if ent else '-'}"
            f" | drop_frac p90 {_fmt(drop['p90'], 4) if drop else '-'}"
            f" | max_group_frac p90"
            f" {_fmt(imbal['p90'], 3) if imbal else '-'}{flag}")
    r = report.get("recompiles")
    if r:
        flag = "  ** RECOMPILE STORM (loader shape churn?) **" if r["storm"] else ""
        lines.append(f"recompiles {r['count']} at steps {r['steps']}"
                     f" shapes {r['shapes']}{flag}")
    for rb in report.get("rollbacks", []):
        lines.append(f"rollback at step {rb['step']} ({rb['cause']})"
                     f" -> restored step {rb['restored_step']}")
    s = report.get("serve")
    if s:
        lines.append(
            f"serve   {_fmt(s.get('tokens_per_s'), 0)} tok/s"
            f" ({s.get('n_requests')} reqs @ {s.get('concurrency')})"
            f" | TTFT p50 {_fmt((s.get('ttft_p50_s') or 0) * 1e3, 1)}ms"
            f" p99 {_fmt((s.get('ttft_p99_s') or 0) * 1e3, 1)}ms"
            f" | TPOT p50 {_fmt((s.get('tpot_p50_s') or 0) * 1e3, 1)}ms"
            f" p99 {_fmt((s.get('tpot_p99_s') or 0) * 1e3, 1)}ms")
        lines.append(
            f"serve   occupancy mean {_fmt(s.get('occupancy_mean'))}"
            f" max {_fmt(s.get('occupancy_max'))}"
            f" | preemptions {s.get('preemptions')}"
            + (f" | {_fmt(s.get('concurrent_speedup'))}x vs sequential"
               if s.get("concurrent_speedup") is not None else ""))
        if s.get("prefill_chunk") or s.get("prefix_cache"):
            lines.append(
                f"serve   chunk {s.get('prefill_chunk') or '-'}"
                f" ({s.get('prefill_chunks') or 0} chunks)"
                f" | prefix hit rate {_fmt(s.get('prefix_hit_rate'))}"
                f" ({s.get('prefix_hit_tokens') or 0}"
                f"/{s.get('prompt_tokens') or 0} prompt tokens,"
                f" {s.get('prefix_evictions') or 0} evictions)")
        if s.get("tp") and s.get("tp") > 1:
            wire = ""
            if s.get("wire_bytes_per_worker") is not None:
                wire = (f" | wire/worker {s['wire_bytes_per_worker']} B"
                        f" ({_fmt(s.get('wire_ratio'))}x full/tp)")
            match = s.get("tp_token_match")
            lines.append(
                f"serve   tp {s['tp']}:"
                f" {s.get('device_pool_blocks')} blocks/device"
                f" x{s['tp']} = {s.get('total_pool_blocks')} total"
                f" (peak {s.get('peak_pool_blocks', '-')})"
                f"{wire}"
                + ("" if match is None else
                   f" | token match {'ok' if match else 'DIVERGED'}"))
        if s.get("spec") and s.get("spec") != "off":
            lines.append(
                f"serve   spec {s['spec']} k={s.get('spec_k')}:"
                f" {_fmt(s.get('spec_accept_mean'))} accepted drafts/step"
                f" (rate {_fmt(s.get('spec_accept_rate'))},"
                f" {s.get('spec_accepted') or 0}"
                f"/{s.get('spec_drafted') or 0} over"
                f" {s.get('spec_steps') or 0} verify steps)"
                f" hist {s.get('spec_accept_hist')}")
    tpp = report.get("tp_parity")
    if tpp:
        lines.append(
            f"tp      parity tp={tpp['tp']}: {tpp['records']} sharded"
            f" lanes, {tpp['mismatched']} diverged"
            + (f" ({', '.join(str(x) for x in tpp['mismatched_lanes'])})"
               f"  ** SHARDED STREAMS DIVERGED **"
               if tpp["mismatched"] else " (all bit-exact)"))
    fe = report.get("frontend")
    if fe:
        lines.append(
            f"frontend {fe.get('replicas_live')}/{fe.get('replicas')}"
            f" replicas ({fe.get('routing')} routing, lane"
            f" {fe.get('lane')}) | {_fmt(fe.get('tokens_per_s'), 0)} tok/s"
            f" aggregate | TTFT p99"
            f" {_fmt((fe.get('ttft_p99_s') or 0) * 1e3, 1)}ms")
        lines.append(
            f"frontend {fe.get('accepted')}/{fe.get('submitted')} accepted"
            f" (reject rate {_fmt(fe.get('reject_rate'), 3)})"
            f" | load imbalance mean {_fmt(fe.get('load_imbalance_mean'))}"
            f" max {_fmt(fe.get('load_imbalance_max'))}"
            f" | failovers {fe.get('failover_events') or 0}"
            f" ({fe.get('failed_over_requests') or 0} reqs)")
        if (fe.get("cancelled") or fe.get("deadline_exceeded")
                or fe.get("deadline_miss_rate") is not None):
            line = (f"frontend lifecycle: {fe.get('finished') or 0} finished,"
                    f" {fe.get('cancelled') or 0} cancelled,"
                    f" {fe.get('deadline_exceeded') or 0} deadline_exceeded")
            if fe.get("deadline_miss_rate") is not None:
                line += (
                    f" | deadline miss rate"
                    f" {_fmt(fe.get('deadline_miss_rate'), 3)} slack p99"
                    f" {_fmt(fe.get('deadline_miss_slack_p99'), 3)}s")
            lines.append(line)
        if fe.get("stall_recovery_max_s") is not None:
            lines.append(
                f"frontend max failover stall"
                f" {_fmt(fe.get('stall_recovery_max_s'), 2)}s"
                f" ({fe.get('fenced') or 0} fenced)")
        if fe.get("transport") == "rpc":
            line = (f"frontend transport rpc ({fe.get('workers')} worker"
                    f" processes, {fe.get('worker_deaths') or 0} deaths)")
            if fe.get("rpc_overhead_p99_s") is not None:
                line += (
                    f" | RPC overhead p50"
                    f" {_fmt((fe.get('rpc_overhead_p50_s') or 0) * 1e3, 1)}ms"
                    f" p99"
                    f" {_fmt((fe.get('rpc_overhead_p99_s') or 0) * 1e3, 1)}ms")
            if fe.get("tok_s_vs_inproc") is not None:
                line += f" | tok/s x{_fmt(fe.get('tok_s_vs_inproc'))} vs in-process"
            lines.append(line)
        if fe.get("tp") and fe.get("tp") > 1:
            line = (f"frontend tp {fe['tp']} per replica:"
                    f" {fe.get('device_pool_blocks')} blocks/device"
                    f" x{fe['tp']} = {fe.get('total_pool_blocks')} total")
            if fe.get("wire_bytes_per_worker") is not None:
                line += (f" | wire/worker {fe['wire_bytes_per_worker']} B"
                         f" ({_fmt(fe.get('wire_ratio'))}x full/tp)")
            lines.append(line)
        ab = fe.get("ab")
        if ab:
            lines.append(
                f"frontend A/B {ab.get('routing')} hit rate"
                f" {_fmt(ab.get('prefix_hit_rate'))} vs random"
                f" {_fmt(ab.get('random_prefix_hit_rate'))}"
                + (f" | tok/s x{_fmt(ab.get('tok_s_vs_random'))}"
                   if ab.get("tok_s_vs_random") is not None else ""))
    dis = report.get("disagg")
    if dis:
        line = (f"disagg  lane {dis.get('lane')}: fleet prefix hit"
                f" {_fmt(dis.get('fleet_prefix_hit_rate'))}")
        if dis.get("baseline_prefix_hit_rate") is not None:
            line += (f" vs per-replica baseline"
                     f" {_fmt(dis.get('baseline_prefix_hit_rate'))}")
        if dis.get("roles"):
            line += f" | roles {'/'.join(str(r) for r in dis['roles'])}"
        lines.append(line)
        lines.append(
            f"disagg  store-hit tokens {dis.get('store_hit_tokens') or 0}"
            f" (host {dis.get('store_hit_tokens_host') or 0} / disk"
            f" {dis.get('store_hit_tokens_disk') or 0})"
            f" | migrations {dis.get('migrations') or 0}"
            f" ({dis.get('migrated_bytes') or 0} B)")
        if dis.get("records"):
            lines.append(
                f"disagg  parity: {dis['records']} store lanes vs"
                f" single-engine pin, {dis['mismatched']} diverged"
                + (f" ({', '.join(str(x) for x in dis['mismatched_lanes'])})"
                   f"  ** MIGRATED STREAMS DIVERGED **"
                   if dis["mismatched"] else " (all bit-exact)"))
    sp = report.get("spans")
    if sp:
        flag = "" if sp.get("conservation_ok") else (
            f"  ** SPAN CONSERVATION BROKEN"
            f" ({len(sp.get('open') or [])} open,"
            f" {len(sp.get('multi_terminal') or [])} multi-terminal) **")
        ph = sp.get("phases") or {}

        def _ph(name):
            d = ph.get(name)
            if not d:
                return f"{name} -"
            return (f"{name} p50 {_fmt(d['p50'] * 1e3, 1)}ms"
                    f" p99 {_fmt(d['p99'] * 1e3, 1)}ms")

        lines.append(
            f"spans   {sp['n']} requests (lane {sp.get('lane')})"
            f" | {_ph('queue_wait')} | {_ph('prefill')}"
            f" | {_ph('decode')}{flag}")
        wf = sp.get("waterfall") or []
        if wf:
            lines.append("spans   worst-total waterfall"
                         " (queue|prefill|decode, ms):")
            for w in wf:
                lines.append(
                    f"spans     rid {w.get('rid')}"
                    + (f" r{w.get('replica')}"
                       if w.get("replica") is not None else "")
                    + f"  {_fmt((w.get('queue_wait_s') or 0) * 1e3, 1)}"
                    + f" | {_fmt((w.get('prefill_s') or 0) * 1e3, 1)}"
                    + f" | {_fmt((w.get('decode_s') or 0) * 1e3, 1)}"
                    + f"  = {_fmt((w.get('total_s') or 0) * 1e3, 1)}"
                    + f" ({w.get('n_events')} events)")
    sts = report.get("serve_ts")
    if sts:
        parts = []
        for k in ("dispatch", "host_sched", "rpc_wait", "idle"):
            v = sts.get(f"{k}_frac")
            if v is not None:
                parts.append(f"{k} {_fmt(v * 100, 1)}%")
        parts.append(
            f"untracked {_fmt((sts.get('untracked_frac') or 0) * 100, 1)}%")
        lines.append(
            f"serve_ts {sts['n']} samples over"
            f" {_fmt(sts.get('total_seconds'), 1)}s | " + "  ".join(parts))
        qd = sts.get("queue_depth")
        if qd:
            spark = _sparkline(sts.get("queue_depth_series") or [])
            lines.append(
                f"serve_ts queue depth p50 {_fmt(qd['p50'], 1)}"
                f" p90 {_fmt(qd['p90'], 1)}"
                + (f"  {spark}" if spark else ""))
    inc = report.get("incidents")
    if inc:
        reasons = "  ".join(f"{k} x{v}"
                            for k, v in sorted(inc["by_reason"].items()))
        lines.append(
            f"incidents {inc['n']} ({reasons})"
            + (f" | dumps: {len(inc['dumps'])}" if inc.get("dumps") else ""))
    src = report.get("sources")
    if src:
        parts = "  ".join(
            f"{name} {_fmt(v['loss']['p50'], 4)} (n={v['n']})"
            for name, v in src.items())
        lines.append(f"sources p50 loss by data_source: {parts}")
    d = report.get("decode")
    if d:
        tbl = "  ".join(f"{k} {_fmt(v, 0)}"
                        for k, v in sorted(d["paths"].items()))
        lines.append(f"decode  tok/s: {tbl}")
    el = report.get("elastic")
    if el:
        deaths = "  ".join(f"host{d['host']}({d['cause']})"
                           for d in el["deaths"]) or "none"
        worlds = "  ".join(f"{a}→{b}" for a, b in el["worlds"])
        lines.append(
            f"elastic {el['restarts']} restart(s) | deaths: {deaths}"
            + (f" | world {worlds}" if worlds else "")
            + f" | recovery total {_fmt(el.get('recovery_seconds_total'), 1)}s"
              f" max {_fmt(el.get('recovery_seconds_max'), 1)}s"
            + (f" | supervisor exit {el['supervisor_exit_code']}"
               if el.get("supervisor_exit_code") is not None else ""))
        if el.get("grows"):
            gworlds = "  ".join(f"{a}→{b}" for a, b in el["grow_worlds"])
            lines.append(
                f"regrow  {el['grows']} grow(s)"
                + (f" | world {gworlds}" if gworlds else "")
                + f" | grow total {_fmt(el.get('grow_seconds_total'), 1)}s"
                  f" max {_fmt(el.get('grow_seconds_max'), 1)}s"
                + (f" | standby promotions {el['standby_promotions']}"
                   if el.get("standby_promotions") else ""))
    return lines


# --- the regression gate ---------------------------------------------------

def compare(base: dict, new: dict, *, tok_tol: float = 0.10,
            mfu_tol: float = 0.10, mem_tol: float = 0.10,
            loss_tol: float = 0.05, overhead_tol: float = 0.10,
            serve_lat_tol: float = 0.25,
            recovery_tol: float = 120.0,
            grow_tol: float = 120.0,
            pack_tol: float = 0.05,
            plan_tol: float = 0.30,
            moe_drop_tol: float = 0.0,
            spec_accept_tol: float = 0.0,
            reject_tol: float = 0.05,
            rpc_overhead_tol: float = 1.0,
            deadline_miss_tol: float = 0.05,
            stall_recovery_tol: float = 30.0,
            queue_wait_tol: float = 1.0,
            tp_parity_tol: float = 0.0,
            fleet_hit_tol: float = 0.05) -> List[dict]:
    """PASS/FAIL/SKIP verdicts for ``new`` against baseline ``base``.

    Relative regressions at or beyond the tolerance FAIL (so exactly-10%
    tok/s loss fails the default gate); metrics absent from either run
    SKIP (CPU runs have no MFU or HBM) — SKIP never fails CI.

    ``overlap_overhead`` is an ABSOLUTE gate: the goodput share lost
    to ``checkpoint_save + data_wait``. The overlap engine (ISSUE 4) exists
    to keep that share near zero, so a run whose combined share grows by
    >= ``overhead_tol`` (fraction-of-wall-clock points, not relative — a
    0.1% -> 0.2% doubling is noise, 2% -> 12% is a broken overlap) FAILs.

    Four elastic gates cover chaos-lane runs (recovery/restarts from
    ISSUE 7, grow/regrow from ISSUE 9):

    - ``recovery_seconds_max`` is ABSOLUTE too, but against a fixed
      budget rather than the baseline: the slowest single host-death
      recovery (death detected -> first post-restart heartbeat) must stay
      under ``recovery_tol`` seconds regardless of what the baseline did
      — a recovery that was already slow must not grandfather itself in.
    - ``elastic_restarts`` fails when the new run needed MORE restarts
      than the baseline of the same chaos scenario (each injected fault
      should cost exactly one restart; a second one means the first
      recovery itself died). SKIP when the baseline has no elastic
      records to anchor the count.
    - ``grow_seconds_max`` mirrors the recovery gate for the way back up:
      the slowest single grow-back (capacity grant detected -> first
      heartbeat at the larger world, which includes the graceful drain of
      the smaller attempt) must stay under ``grow_tol`` seconds ABSOLUTE.
    - ``elastic_regrow`` fails when the new run ran with ``allow_grow``,
      lost hosts, and still finished below its desired world — capacity
      came back (or never did) and the run stayed shrunk. SKIP when the
      run didn't opt into growing or lost nothing.

    ``non_pad_frac`` is ABSOLUTE as well: the packed-data non-pad token
    fraction dropping by >= ``pack_tol`` fraction points against the
    baseline FAILs (bin-packing efficiency regressed — first-fit heuristic
    change, bin-flush bug, loader reorder). Relative would mis-scale: a
    0.98 -> 0.93 drop and a 0.40 -> 0.38 drop are both ~5% relative but
    only the first burns five points of paid-for compute. SKIP when either
    run doesn't track packing.

    ``plan_error_frac`` is ABSOLUTE against a fixed budget, like the
    elastic gates: the mesh auto-planner's median predicted-vs-measured
    step-time error (parallel/planner.py, bench.py's per-window
    ``plan_error_frac``) must stay under ``plan_tol`` regardless of the
    baseline — a cost model that's 50% off misranks meshes whether or not
    it was 50% off last week. SKIP when the run carries no mesh_plan
    record with a measured step time.

    ``moe_drop_frac`` is ABSOLUTE against a fixed budget too, and the
    budget defaults to zero: a run whose router telemetry says
    ``moe_impl="dropless"`` (the ``dropless`` marker scalar) must log
    ``drop_frac == 0`` at every captured step — dropless routing admits
    every token by construction (models/moe.py ``_dropless_ffn``), so any
    nonzero drop means the permutation/bincount path is broken. FAIL when
    the worst captured drop_frac exceeds ``moe_drop_tol``; SKIP for
    capacity-mode or non-MoE runs (drops there are a tuning choice, not a
    bug).

    Three front-end gates cover multi-replica serving runs (``kind=
    "frontend"`` records from ``serve_bench --replicas`` /
    ``--workers``):

    - ``frontend_reject_rate`` is ABSOLUTE against a fixed ceiling:
      the share of submitted requests shed at admission must stay under
      ``reject_tol`` regardless of the baseline — backpressure is a
      safety valve, and a valve that is open 20% of the time is an
      undersized fleet (or a routing bug piling work on one replica),
      not a healthy steady state. SKIP when the run has no frontend
      records.
    - ``frontend_affinity`` is categorical: in a routing A/B
      (``serve_bench --ab`` stamps the policy lane's record with the
      random lane's ``random_prefix_hit_rate``), the affinity policy's
      aggregate prefix hit rate must not fall below the random-routing
      baseline measured in the same run. Affinity routing exists only
      to buy cache hits; losing to a coin flip means the key, the
      rendezvous hash, or the spill threshold is broken. SKIP when the
      record set carries no A/B pair.
    - ``frontend_rpc_overhead`` is ABSOLUTE against a fixed budget:
      the p99 per-request RPC overhead of cross-process serving
      (``serve_bench --workers --ab`` stamps the rpc lane's record with
      the submit-to-first-token delta vs the identical in-process fleet
      on the same trace) must stay under ``rpc_overhead_tol`` seconds.
      SKIP on in-process runs (no rpc record, or no A/B delta).
    - ``frontend_deadline_miss`` is ABSOLUTE against a fixed ceiling:
      the fraction of deadline-carrying terminal requests that finished
      (or expired) past their deadline must stay under
      ``deadline_miss_tol`` — an SLO is a promise, not a baseline-
      relative metric. SKIP when the run carried no deadlines (the
      metric is only emitted when deadline margins were observed).
    - ``frontend_stall_recovery`` is ABSOLUTE against a fixed budget:
      the longest single front-end stall on a replica step that ended
      in failover (a hung worker fenced at the RPC timeout, or a death
      mid-call) must stay under ``stall_recovery_tol`` seconds — the
      per-call timeout exists precisely to bound this. SKIP when the
      run had no such stall.
    - ``frontend_fleet_hit`` is ABSOLUTE in fraction points against the
      baseline run: the fleet-wide token-weighted prefix hit rate (device
      hits plus store-fill hits, serve_bench ``--disagg`` /
      ``--kv-store-mb``) dropping by >= ``fleet_hit_tol`` points means
      the digest-addressed store stopped rescuing cross-replica misses —
      a store that hid 60% of prefill yesterday and 40% today is a real
      capacity loss even if both clear some relative bar. Relative would
      mis-scale exactly like ``non_pad_frac``. SKIP when either run has
      no fleet hit rate.
    - ``frontend_disagg_parity`` is categorical, like tp parity: every
      lane that serve_bench pinned against a single undisturbed engine
      (``disagg_token_match``) must match bit-exactly — migration moves
      K/V blocks, never token distributions, so ANY diverged migrated
      stream is a codec/fill/ordering bug, not a regression to tolerate.
      SKIP when the new run pinned nothing.
    """
    def get(report, *keys):
        cur = report
        for k in keys:
            if not isinstance(cur, dict) or cur.get(k) is None:
                return None
            cur = cur[k]
        return cur

    specs = [
        ("tok_per_sec_p50", ("train", "tok_per_sec", "p50"), "higher", tok_tol),
        ("mfu_p50", ("train", "mfu", "p50"), "higher", mfu_tol),
        ("peak_mem_gb", ("train", "peak_mem_gb"), "lower", mem_tol),
        ("final_loss", ("train", "final_loss"), "lower", loss_tol),
        # Serving (serve_bench.py) and decode (decode_bench.py) records:
        # throughput gates share tok_tol; latency gets the looser
        # serve_lat_tol (tail latency is noisier than aggregate tok/s).
        ("serve_tok_per_sec", ("serve", "tokens_per_s"), "higher", tok_tol),
        ("serve_ttft_p99_s", ("serve", "ttft_p99_s"), "lower", serve_lat_tol),
        ("serve_tpot_p99_s", ("serve", "tpot_p99_s"), "lower", serve_lat_tol),
        # Prefix-cache effectiveness: a hit rate dropping against the
        # baseline means sharing broke (digest change, eviction bug, cursor
        # regression). SKIPs when either run didn't serve with the cache on
        # (older records carry no hit rate; a zero baseline is skipped by
        # the b == 0 guard below rather than dividing by it).
        ("serve_prefix_hit_rate",
         ("serve", "prefix_hit_rate"), "higher", serve_lat_tol),
        ("decode_kv_tok_per_sec",
         ("decode", "kv_best_tok_per_sec"), "higher", tok_tol),
        ("effective_tok_per_sec_p50",
         ("packing", "effective_tok_per_sec", "p50"), "higher", tok_tol),
    ]
    verdicts = []
    eps = 1e-9
    for name, keys, better, tol in specs:
        b, n = get(base, *keys), get(new, *keys)
        if b is None or n is None or b == 0:
            verdicts.append({"metric": name, "verdict": "SKIP",
                             "base": b, "new": n})
            continue
        delta = (n - b) / abs(b)
        regression = -delta if better == "higher" else delta
        verdicts.append({
            "metric": name,
            "verdict": "FAIL" if regression >= tol - eps else "PASS",
            "base": b,
            "new": n,
            "delta_pct": round(delta * 100, 2),
            "tolerance_pct": round(tol * 100, 2),
        })

    def overhead(report):
        fr = get(report, "goodput", "fractions")
        if fr is None:
            return None
        vals = [fr.get("checkpoint_save"), fr.get("data_wait")]
        if all(v is None for v in vals):
            return None
        return sum(v for v in vals if v is not None)

    b, n = overhead(base), overhead(new)
    if b is None or n is None:
        verdicts.append({"metric": "overlap_overhead", "verdict": "SKIP",
                         "base": b, "new": n})
    else:
        delta = n - b  # absolute, in fraction-of-wall-clock points
        verdicts.append({
            "metric": "overlap_overhead",
            "verdict": "FAIL" if delta >= overhead_tol - eps else "PASS",
            "base": round(b, 4),
            "new": round(n, 4),
            "delta_pct": round(delta * 100, 2),
            "tolerance_pct": round(overhead_tol * 100, 2),
            "absolute": True,
        })

    b_frac = get(base, "packing", "non_pad_frac")
    n_frac = get(new, "packing", "non_pad_frac")
    if b_frac is None or n_frac is None:
        verdicts.append({"metric": "non_pad_frac", "verdict": "SKIP",
                         "base": b_frac, "new": n_frac})
    else:
        delta = b_frac - n_frac  # absolute, in fraction points
        verdicts.append({
            "metric": "non_pad_frac",
            "verdict": "FAIL" if delta >= pack_tol - eps else "PASS",
            "base": round(b_frac, 4),
            "new": round(n_frac, 4),
            "delta_pct": round(-delta * 100, 2),
            "tolerance_pct": round(pack_tol * 100, 2),
            "absolute": True,
        })

    # Planner prediction-quality gate: only a run that actually measured
    # (bench) carries measured_step_ms; a training CLI --mesh auto run
    # logs the plan without one and SKIPs.
    new_plan_err = (get(new, "plan", "plan_error_frac")
                    if get(new, "plan", "measured_step_ms") is not None
                    else None)
    if new_plan_err is None:
        verdicts.append({"metric": "plan_error_frac", "verdict": "SKIP",
                         "base": get(base, "plan", "plan_error_frac"),
                         "new": None})
    else:
        verdicts.append({
            "metric": "plan_error_frac",
            "verdict": "FAIL" if new_plan_err >= plan_tol - eps else "PASS",
            "base": get(base, "plan", "plan_error_frac"),
            "new": round(new_plan_err, 4),
            "tolerance_frac": plan_tol,
            "absolute": True,
        })

    # Dropless-MoE correctness gate: only gates runs that SAY they are
    # dropless; the worst drop_frac across captured steps must stay at (or
    # under) the absolute budget, baseline irrelevant.
    new_drop_max = (get(new, "router", "drop_frac_max")
                    if get(new, "router", "dropless") else None)
    if new_drop_max is None:
        verdicts.append({"metric": "moe_drop_frac", "verdict": "SKIP",
                         "base": get(base, "router", "drop_frac_max"),
                         "new": None})
    else:
        verdicts.append({
            "metric": "moe_drop_frac",
            "verdict": "FAIL" if new_drop_max > moe_drop_tol + eps else "PASS",
            "base": get(base, "router", "drop_frac_max"),
            "new": round(new_drop_max, 6),
            "tolerance_frac": moe_drop_tol,
            "absolute": True,
        })

    # Speculative-decode acceptance gate: only gates runs whose serve
    # record ran with a proposer; mean accepted drafts per verify step
    # must clear the absolute floor (0.0 default = always passes — set
    # per workload, e.g. --spec-accept-tol 1.0 on a repetitive trace).
    new_accept = (get(new, "serve", "spec_accept_mean")
                  if (get(new, "serve", "spec") or "off") != "off" else None)
    if new_accept is None:
        verdicts.append({"metric": "spec_accept_mean", "verdict": "SKIP",
                         "base": get(base, "serve", "spec_accept_mean"),
                         "new": None})
    else:
        verdicts.append({
            "metric": "spec_accept_mean",
            "verdict": ("FAIL" if new_accept < spec_accept_tol - eps
                        else "PASS"),
            "base": get(base, "serve", "spec_accept_mean"),
            "new": round(new_accept, 4),
            "tolerance": spec_accept_tol,
            "absolute": True,
        })

    new_rec_max = get(new, "elastic", "recovery_seconds_max")
    if new_rec_max is None:
        verdicts.append({"metric": "recovery_seconds_max", "verdict": "SKIP",
                         "base": get(base, "elastic", "recovery_seconds_max"),
                         "new": None})
    else:
        verdicts.append({
            "metric": "recovery_seconds_max",
            "verdict": "FAIL" if new_rec_max >= recovery_tol - eps else "PASS",
            "base": get(base, "elastic", "recovery_seconds_max"),
            "new": round(new_rec_max, 2),
            "tolerance_s": recovery_tol,
            "absolute": True,
        })

    b_restarts = get(base, "elastic", "restarts")
    n_restarts = get(new, "elastic", "restarts")
    if b_restarts is None or n_restarts is None:
        verdicts.append({"metric": "elastic_restarts", "verdict": "SKIP",
                         "base": b_restarts, "new": n_restarts})
    else:
        verdicts.append({
            "metric": "elastic_restarts",
            "verdict": "FAIL" if n_restarts > b_restarts else "PASS",
            "base": b_restarts,
            "new": n_restarts,
            "absolute": True,
        })

    new_grow_max = get(new, "elastic", "grow_seconds_max")
    if new_grow_max is None:
        verdicts.append({"metric": "grow_seconds_max", "verdict": "SKIP",
                         "base": get(base, "elastic", "grow_seconds_max"),
                         "new": None})
    else:
        verdicts.append({
            "metric": "grow_seconds_max",
            "verdict": "FAIL" if new_grow_max >= grow_tol - eps else "PASS",
            "base": get(base, "elastic", "grow_seconds_max"),
            "new": round(new_grow_max, 2),
            "tolerance_s": grow_tol,
            "absolute": True,
        })

    # Failure-to-regrow: a run that lost hosts under --allow_grow and
    # finished BELOW the world it wanted never got back up — the grow
    # probe, capacity protocol, or relaunch is broken even if every
    # recovery individually passed.
    n_el = new.get("elastic") if isinstance(new.get("elastic"), dict) else {}
    wants_regrow = (n_el.get("allow_grow") and n_el.get("deaths")
                    and n_el.get("desired_world") is not None
                    and n_el.get("final_world") is not None)
    if not wants_regrow:
        verdicts.append({"metric": "elastic_regrow", "verdict": "SKIP",
                         "base": None, "new": n_el.get("final_world")})
    else:
        verdicts.append({
            "metric": "elastic_regrow",
            "verdict": ("PASS" if n_el["final_world"] >= n_el["desired_world"]
                        else "FAIL"),
            "base": n_el["desired_world"],
            "new": n_el["final_world"],
            "absolute": True,
        })

    new_reject = get(new, "frontend", "reject_rate")
    if new_reject is None:
        verdicts.append({"metric": "frontend_reject_rate", "verdict": "SKIP",
                         "base": get(base, "frontend", "reject_rate"),
                         "new": None})
    else:
        verdicts.append({
            "metric": "frontend_reject_rate",
            "verdict": "FAIL" if new_reject > reject_tol + eps else "PASS",
            "base": get(base, "frontend", "reject_rate"),
            "new": round(new_reject, 4),
            "tolerance_frac": reject_tol,
            "absolute": True,
        })

    # Sharded-decode parity is categorical, like span conservation: a
    # tensor-parallel lane whose greedy stream diverges from its
    # unsharded (or no-fault) base lane leaked the sharded compute path
    # into the tokens — exactness is by construction, so ANY mismatch
    # past ``tp_parity_tol`` (a fraction of sharded lanes, default 0 —
    # one diverged lane fails) is a bug, not a regression to tolerate.
    # SKIP when the new run served nothing sharded.
    n_tpp = get(new, "tp_parity") or {}
    if not n_tpp:
        verdicts.append({"metric": "serve_tp_parity", "verdict": "SKIP",
                         "base": (get(base, "tp_parity") or {}).get(
                             "mismatched"),
                         "new": None})
    else:
        frac = n_tpp["mismatched"] / max(n_tpp["records"], 1)
        verdicts.append({
            "metric": "serve_tp_parity",
            "verdict": "FAIL" if frac > tp_parity_tol + eps else "PASS",
            "base": (get(base, "tp_parity") or {}).get("mismatched"),
            "new": n_tpp["mismatched"],
            "tolerance": tp_parity_tol,
            "absolute": True,
        })

    # Affinity-vs-random A/B (both hit rates come from the SAME run's
    # record set — see summarize — so this never compares across trees).
    n_ab = get(new, "frontend", "ab") or {}
    aff_hit = n_ab.get("prefix_hit_rate")
    rnd_hit = n_ab.get("random_prefix_hit_rate")
    if aff_hit is None or rnd_hit is None:
        verdicts.append({"metric": "frontend_affinity", "verdict": "SKIP",
                         "base": None, "new": aff_hit})
    else:
        verdicts.append({
            "metric": "frontend_affinity",
            "verdict": "FAIL" if aff_hit < rnd_hit - eps else "PASS",
            "base": round(rnd_hit, 4),
            "new": round(aff_hit, 4),
            "absolute": True,
        })

    # RPC overhead is ABSOLUTE against a fixed budget, like the elastic
    # gates: the p99 per-request submit-to-first-token cost of the wire
    # (measured by serve_bench --workers --ab against the identical
    # in-process fleet on the same trace) must stay under
    # ``rpc_overhead_tol`` seconds regardless of the baseline — framing
    # + socket dispatch costing a second per request is broken whether
    # or not it was broken last week. SKIP on in-process runs (no rpc
    # record or no A/B to measure the delta against).
    new_ovh = get(new, "frontend", "rpc_overhead_p99_s")
    if new_ovh is None:
        verdicts.append({"metric": "frontend_rpc_overhead",
                         "verdict": "SKIP",
                         "base": get(base, "frontend", "rpc_overhead_p99_s"),
                         "new": None})
    else:
        verdicts.append({
            "metric": "frontend_rpc_overhead",
            "verdict": "FAIL" if new_ovh > rpc_overhead_tol + eps else "PASS",
            "base": get(base, "frontend", "rpc_overhead_p99_s"),
            "new": round(new_ovh, 5),
            "tolerance_s": rpc_overhead_tol,
            "absolute": True,
        })

    # Deadline misses and hung-RPC stalls are ABSOLUTE against fixed
    # budgets: an SLO miss rate or a failover stall that was already bad
    # in the baseline must not grandfather itself in. Both SKIP when the
    # run never observed the metric (no deadlines attached; no failover
    # stall) — emission is conditional in frontend.summary() for exactly
    # this reason.
    new_miss = get(new, "frontend", "deadline_miss_rate")
    if new_miss is None:
        verdicts.append({"metric": "frontend_deadline_miss",
                         "verdict": "SKIP",
                         "base": get(base, "frontend", "deadline_miss_rate"),
                         "new": None})
    else:
        verdicts.append({
            "metric": "frontend_deadline_miss",
            "verdict": "FAIL" if new_miss > deadline_miss_tol + eps
            else "PASS",
            "base": get(base, "frontend", "deadline_miss_rate"),
            "new": round(new_miss, 5),
            "tolerance_frac": deadline_miss_tol,
            "absolute": True,
        })
    new_stall = get(new, "frontend", "stall_recovery_max_s")
    if new_stall is None:
        verdicts.append({"metric": "frontend_stall_recovery",
                         "verdict": "SKIP",
                         "base": get(base, "frontend",
                                     "stall_recovery_max_s"),
                         "new": None})
    else:
        verdicts.append({
            "metric": "frontend_stall_recovery",
            "verdict": "FAIL" if new_stall > stall_recovery_tol + eps
            else "PASS",
            "base": get(base, "frontend", "stall_recovery_max_s"),
            "new": round(new_stall, 5),
            "tolerance_s": stall_recovery_tol,
            "absolute": True,
        })

    # Fleet-wide prefix hit rate is ABSOLUTE in fraction points against
    # the baseline run (the disagg summary's store-bearing lane wins,
    # falling back to the newest frontend record): the whole point of
    # the fleet store is that rate, so it regresses in points, not
    # percent-of-itself.
    def fleet_hit(report):
        v = get(report, "disagg", "fleet_prefix_hit_rate")
        return v if v is not None else get(
            report, "frontend", "fleet_prefix_hit_rate")

    b_fleet, n_fleet = fleet_hit(base), fleet_hit(new)
    if b_fleet is None or n_fleet is None:
        verdicts.append({"metric": "frontend_fleet_hit", "verdict": "SKIP",
                         "base": b_fleet, "new": n_fleet})
    else:
        delta = b_fleet - n_fleet  # absolute, in fraction points
        verdicts.append({
            "metric": "frontend_fleet_hit",
            "verdict": "FAIL" if delta >= fleet_hit_tol - eps else "PASS",
            "base": round(b_fleet, 4),
            "new": round(n_fleet, 4),
            "tolerance_frac": fleet_hit_tol,
            "absolute": True,
        })

    # Migrated-stream parity is categorical, like tp parity and span
    # conservation: any lane whose streams diverged from the
    # single-engine pin FAILs, whatever the baseline did.
    n_dis = get(new, "disagg") or {}
    if not n_dis.get("records"):
        verdicts.append({"metric": "frontend_disagg_parity",
                         "verdict": "SKIP",
                         "base": (get(base, "disagg") or {}).get(
                             "mismatched"),
                         "new": None})
    else:
        verdicts.append({
            "metric": "frontend_disagg_parity",
            "verdict": "FAIL" if n_dis["mismatched"] else "PASS",
            "base": (get(base, "disagg") or {}).get("mismatched"),
            "new": n_dis["mismatched"],
            "absolute": True,
        })

    # Queue-wait p99 is ABSOLUTE against a fixed budget: admission-to-
    # arrival latency is an SLO input, not a baseline-relative number —
    # a queue that was already slow must not grandfather itself in.
    # Preferred source: the span-trace phase percentiles (spans carry
    # the true first-admission wait even across failover); falls back to
    # the serve/frontend records' queue_wait series. SKIP when the run
    # traced no queue waits at all.
    new_qw = get(new, "spans", "phases", "queue_wait", "p99")
    if new_qw is None:
        new_qw = get(new, "serve", "queue_wait_p99_s")
    if new_qw is None:
        new_qw = get(new, "frontend", "queue_wait_p99_s")
    if new_qw is None:
        verdicts.append({"metric": "serve_queue_wait_p99",
                         "verdict": "SKIP",
                         "base": get(base, "spans", "phases",
                                     "queue_wait", "p99"),
                         "new": None})
    else:
        verdicts.append({
            "metric": "serve_queue_wait_p99",
            "verdict": "FAIL" if new_qw > queue_wait_tol + eps else "PASS",
            "base": get(base, "spans", "phases", "queue_wait", "p99"),
            "new": round(new_qw, 5),
            "tolerance_s": queue_wait_tol,
            "absolute": True,
        })

    # Span conservation is CATEGORICAL: every opened rid in the new
    # run's span records must close with exactly one terminal event
    # (or an explicit handoff). A dropped or doubled terminal is a
    # bookkeeping bug whatever the baseline did. SKIP when the run
    # emitted no span records.
    new_cons = get(new, "spans", "conservation_ok")
    if new_cons is None:
        verdicts.append({"metric": "span_conservation", "verdict": "SKIP",
                         "base": get(base, "spans", "conservation_ok"),
                         "new": None})
    else:
        verdicts.append({
            "metric": "span_conservation",
            "verdict": "PASS" if new_cons else "FAIL",
            "base": get(base, "spans", "conservation_ok"),
            "new": bool(new_cons),
            "absolute": True,
        })
    return verdicts


def render_verdicts(verdicts: List[dict]) -> List[str]:
    lines = ["== regression gate (new vs base) =="]
    for v in verdicts:
        if v["verdict"] == "SKIP":
            lines.append(f"SKIP {v['metric']:<16} (absent in one run)")
        elif "delta_pct" in v:
            kind = " abs" if v.get("absolute") else ""
            lines.append(
                f"{v['verdict']} {v['metric']:<16} base {_fmt(v['base'], 4)}"
                f" new {_fmt(v['new'], 4)} ({v['delta_pct']:+.1f}%{kind},"
                f" tol {v['tolerance_pct']:.0f}%{kind})")
        else:
            if v.get("tolerance_s") is not None:
                tol = f", tol {_fmt(v['tolerance_s'], 0)}s abs"
            elif v.get("tolerance_frac") is not None:
                tol = f", tol {_fmt(v['tolerance_frac'] * 100, 0)}% abs"
            elif v.get("tolerance") is not None:
                # Plain-units absolute floor (e.g. accepted tokens/step).
                tol = f", floor {_fmt(v['tolerance'], 2)} abs"
            else:
                tol = ""
            lines.append(
                f"{v['verdict']} {v['metric']:<16} base {_fmt(v['base'], 2)}"
                f" new {_fmt(v['new'], 2)} (absolute{tol})")
    return lines


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m tpu_trainer.tools.analyze",
        description="Analyze a training-run metrics JSONL; optionally gate "
                    "it against a baseline run.")
    parser.add_argument("run", help="metrics JSONL of the run to analyze")
    parser.add_argument("--compare", metavar="BASE",
                        help="baseline JSONL; exit 1 on regression")
    parser.add_argument("--tok-tol", type=float, default=0.10,
                        help="tok/s relative tolerance (default 0.10)")
    parser.add_argument("--mfu-tol", type=float, default=0.10)
    parser.add_argument("--mem-tol", type=float, default=0.10)
    parser.add_argument("--loss-tol", type=float, default=0.05)
    parser.add_argument("--serve-lat-tol", type=float, default=0.25,
                        help="serve p99 TTFT/TPOT relative tolerance "
                             "(default 0.25)")
    parser.add_argument("--overhead-tol", type=float, default=0.10,
                        help="ABSOLUTE gate on the checkpoint_save + "
                             "data_wait goodput share: FAIL if the new "
                             "run's share grows by >= this many fraction-"
                             "of-wall-clock points (default 0.10)")
    parser.add_argument("--pack-tol", type=float, default=0.05,
                        help="ABSOLUTE gate on the packed-data non-pad "
                             "token fraction: FAIL if the new run's "
                             "fraction drops by >= this many fraction "
                             "points vs the baseline (default 0.05)")
    parser.add_argument("--recovery-tol", type=float, default=120.0,
                        help="ABSOLUTE gate on elastic recovery: FAIL if "
                             "any single host-death recovery in the new "
                             "run took >= this many seconds (default 120)")
    parser.add_argument("--grow-tol", type=float, default=120.0,
                        help="ABSOLUTE gate on elastic grow-back: FAIL if "
                             "any single world re-expansion (grant "
                             "detected -> first grown-world heartbeat) "
                             "took >= this many seconds (default 120)")
    parser.add_argument("--plan-tol", type=float, default=0.30,
                        help="ABSOLUTE gate on the mesh auto-planner: FAIL "
                             "if the new run's median predicted-vs-measured "
                             "step-time error is >= this fraction (default "
                             "0.30); SKIP when the run carries no mesh_plan "
                             "record with a measured step time")
    parser.add_argument("--moe-drop-tol", type=float, default=0.0,
                        help="ABSOLUTE gate on dropless-MoE routing: FAIL "
                             "if a run whose router telemetry is marked "
                             "dropless logged drop_frac above this value "
                             "at any captured step (default 0.0 — dropless "
                             "means dropless); SKIP for capacity-mode or "
                             "non-MoE runs")
    parser.add_argument("--spec-accept-tol", type=float, default=0.0,
                        help="ABSOLUTE gate on speculative decoding: FAIL "
                             "if a spec-enabled serve run's mean accepted "
                             "drafts per verify step falls below this floor "
                             "(default 0.0 — always passes); SKIP when the "
                             "new run served without a proposer")
    parser.add_argument("--reject-tol", type=float, default=0.05,
                        help="ABSOLUTE gate on front-end admission: FAIL "
                             "if a multi-replica serving run rejected more "
                             "than this fraction of submitted requests "
                             "(default 0.05); SKIP when the run has no "
                             "frontend records. The affinity-vs-random "
                             "hit-rate gate needs no tolerance: affinity "
                             "losing to random in the same --ab run is a "
                             "categorical FAIL")
    parser.add_argument("--rpc-overhead-tol", type=float, default=1.0,
                        help="ABSOLUTE gate on cross-process serving: FAIL "
                             "if the p99 per-request RPC overhead (the "
                             "submit-to-first-token delta vs the identical "
                             "in-process fleet, serve_bench --workers --ab) "
                             "exceeds this many seconds (default 1.0); SKIP "
                             "on in-process runs")
    parser.add_argument("--deadline-miss-tol", type=float, default=0.05,
                        help="ABSOLUTE gate on request deadlines: FAIL if "
                             "more than this fraction of deadline-carrying "
                             "requests finished or expired past their "
                             "deadline (default 0.05); SKIP when the run "
                             "attached no deadlines")
    parser.add_argument("--stall-recovery-tol", type=float, default=30.0,
                        help="ABSOLUTE gate on failover stalls: FAIL if "
                             "the longest front-end stall on a replica "
                             "step that ended in failover (hung worker "
                             "fenced at the RPC timeout, or death mid-"
                             "call) exceeds this many seconds (default "
                             "30); SKIP when the run had no such stall")
    parser.add_argument("--queue-wait-tol", type=float, default=1.0,
                        help="ABSOLUTE gate on serving queue wait: FAIL if "
                             "the new run's p99 admission-to-arrival wait "
                             "(span traces, else the serve/frontend "
                             "queue_wait series) exceeds this many seconds "
                             "(default 1.0); SKIP when the run traced no "
                             "queue waits. Span conservation needs no "
                             "tolerance: an opened rid without exactly one "
                             "terminal event is a categorical FAIL")
    parser.add_argument("--tp-parity-tol", type=float, default=0.0,
                        help="ABSOLUTE gate on sharded (tensor-parallel) "
                             "decode: FAIL if more than this fraction of "
                             "the run's sharded lanes diverged token-wise "
                             "from their unsharded / no-fault base lane "
                             "(default 0.0 — sharded decode is exact by "
                             "construction, one diverged lane fails); "
                             "SKIP when the run served nothing sharded")
    parser.add_argument("--fleet-hit-tol", type=float, default=0.05,
                        help="ABSOLUTE gate on the fleet-wide token-"
                             "weighted prefix hit rate (device + KV-store "
                             "fills, serve_bench --disagg / --kv-store-mb): "
                             "FAIL if the new run's rate drops by >= this "
                             "many fraction points vs the baseline "
                             "(default 0.05); SKIP when either run has no "
                             "fleet hit rate. Migrated-stream parity vs "
                             "the single-engine pin needs no tolerance: "
                             "any diverged stream is a categorical FAIL")
    parser.add_argument("--json", action="store_true",
                        help="print the report (and verdicts) as JSON")
    args = parser.parse_args(argv)

    try:
        report = summarize(load_records(args.run))
    except SchemaError as e:
        print(f"analyze: {e}", file=sys.stderr)
        return 2

    verdicts = None
    if args.compare:
        try:
            base_report = summarize(load_records(args.compare))
        except SchemaError as e:
            print(f"analyze: {e}", file=sys.stderr)
            return 2
        verdicts = compare(
            base_report, report, tok_tol=args.tok_tol, mfu_tol=args.mfu_tol,
            mem_tol=args.mem_tol, loss_tol=args.loss_tol,
            overhead_tol=args.overhead_tol,
            serve_lat_tol=args.serve_lat_tol,
            recovery_tol=args.recovery_tol, grow_tol=args.grow_tol,
            pack_tol=args.pack_tol, plan_tol=args.plan_tol,
            moe_drop_tol=args.moe_drop_tol,
            spec_accept_tol=args.spec_accept_tol,
            reject_tol=args.reject_tol,
            rpc_overhead_tol=args.rpc_overhead_tol,
            deadline_miss_tol=args.deadline_miss_tol,
            stall_recovery_tol=args.stall_recovery_tol,
            queue_wait_tol=args.queue_wait_tol,
            tp_parity_tol=args.tp_parity_tol,
            fleet_hit_tol=args.fleet_hit_tol)

    exit_code = (1 if verdicts is not None
                 and any(v["verdict"] == "FAIL" for v in verdicts) else 0)
    if args.json:
        # Machine-readable envelope for CI: the full report, the verdict
        # list (each row carries metric / verdict / base / new and, when
        # the gate evaluated, delta + tolerance), a PASS/FAIL/SKIP tally,
        # and the exit code the process is about to return — so a caller
        # parsing stdout never has to re-derive the gate decision.
        gate = None
        if verdicts is not None:
            gate = {k: sum(1 for v in verdicts if v["verdict"] == k)
                    for k in ("PASS", "FAIL", "SKIP")}
        print(json.dumps({"report": report, "verdicts": verdicts,
                          "gate": gate, "exit_code": exit_code}, indent=1))
    else:
        for line in render(report):
            print(line)
        if verdicts is not None:
            for line in render_verdicts(verdicts):
                print(line)
    return exit_code


if __name__ == "__main__":
    sys.exit(main())
